"""The serving engine: continuous batching over a paged KV cache.

A single-process engine instance (one per model, spawned by the launcher)
owning sharded params, the page pool, and two compiled programs:

  * ``_prefill_fn``  — batch-1 prompt ingestion, bucketed to power-of-two
    lengths so at most log2(max_seq) prefill programs are ever compiled;
    samples the first token *inside* the program;
  * ``_chunk_fn(T)`` — T fused decode+sample steps (``lax.scan`` over steps)
    for the whole slot batch, cache donated so page updates are in-place in
    HBM. Exactly two chunk programs ever compile: T = ``decode_chunk``
    (steady state) and T = 1 (drain tail) — compiles are expensive on TPU.

Decode runs every slot every step (static shapes; empty slots write to the
reserved null page and their outputs are ignored) — the XLA-friendly version
of continuous batching: requests join/leave by host-side slot bookkeeping,
the compiled step never changes shape.

The serving path contains NO eager jax ops: scheduler state (last tokens,
positions, per-slot budgets, page table, temperatures, RNG key data) lives in
device arrays threaded through the compiled programs, and the host only
uploads fresh state after an admission/retire edge and downloads the [T, b]
token block once per chunk. This matters twice on TPU: per-op dispatch is
expensive (each eager op is a host round-trip), and eager ops re-specialize
(recompile) when array commitment changes across a sleep/wake cycle — the
reference-framework "wake must not recompile" contract (README.md:16-26)
only holds if the hot path is entirely pre-compiled programs.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence as Seq, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..models import llama
from ..parallel.mesh import shard_pytree
from .kv_cache import OutOfPages, PageAllocator, PagePool
from .sampling import sample


@dataclass(frozen=True)
class EngineConfig:
    model: llama.LlamaConfig
    max_batch: int = 8
    page_size: int = 16
    num_pages: int = 2048
    max_seq_len: int = 0  # 0 -> model.max_seq_len
    eos_token_id: int = -1  # -1 = never stop on EOS
    #: additional stopping ids (Llama-3-Instruct declares [eos, eom, eot];
    #: chat turns end with eot, not the primary eos)
    extra_eos_ids: tuple = ()
    #: top-k alternative logprobs computed per emitted token inside the
    #: compiled programs (OpenAI `logprobs`/`top_logprobs`; vLLM caps at
    #: 5). 0 disables the extra top-k + transfer.
    logprobs_topk: int = 5
    #: Attention implementation: "auto" (pallas on TPU, grouped elsewhere),
    #: "grouped" (GQA-grouped XLA, deferred cache scatter), "pallas"
    #: (hand-written TPU kernels; interpreter mode off-TPU), or "reference"
    #: (scatter-first + repeat-KV XLA — the parity baseline).
    attention_impl: str = "auto"
    #: Max decode steps fused into one compiled program dispatch.
    decode_chunk: int = 8
    #: Automatic prefix caching (engine/prefix_cache.py): page-aligned KV
    #: reuse across requests sharing a prompt prefix. Outputs are
    #: identical with it on or off; on is the serving default (the
    #: reference's engine ships the same as vLLM APC).
    prefix_caching: bool = True
    #: Chunked prefill: prompts longer than this prefill in segments of at
    #: most this many tokens (bounds prefill activation memory and compile
    #: buckets; later segments attend over the paged cache). 0 = off.
    max_prefill_tokens: int = 0
    #: N-gram (prompt-lookup) speculative decoding: propose up to this many
    #: tokens by matching the context's most recent n-gram and verify them
    #: in ONE forward over the paged cache (vLLM's "ngram" speculative
    #: decoding). Every emitted token is the verify forward's own greedy
    #: argmax, so quality equals plain greedy decoding; bitwise equality
    #: with the chunk program is NOT guaranteed at argmax ties (the two
    #: programs reduce bf16 in different orders — the standard spec-decode
    #: caveat). Engages for single-sequence greedy decoding only; 0 = off.
    speculative_ngram: int = 0
    #: Double-buffered decode: dispatch chunk k+1 before reading chunk k's
    #: results, overlapping device compute with the host's fetch+emit —
    #: wins when per-dispatch latency is comparable to chunk compute
    #: (remote/tunneled TPU hosts; docs/perf.md). Token delivery lags one
    #: chunk. Ignored under gang lockstep. Off by default.
    pipeline_decode: bool = False
    #: Drain-tail policy when the batch's max remaining budget is below
    #: decode_chunk: "single" dispatches T=1 steps (minimal wasted
    #: compute — right when dispatch is cheap), "chunk" runs the full
    #: chunk program once (finished slots freeze in-program, so up to
    #: chunk-1 steps idle but up to chunk-1 dispatch round trips are
    #: saved — right on high-latency links, and the T=1 program never
    #: compiles). "auto" = chunk on TPU, single elsewhere. Outputs are
    #: identical either way (chunk-length invariance).
    drain_tail: str = "auto"
    #: Token-packed mixed-batch serving (docs/perf.md "Mixed-batch
    #: serving"): whenever prefill work is pending, ONE compiled
    #: ``mixed`` program processes a flat [token_budget] buffer packing
    #: prefill segments AND one decode row per running sequence, then
    #: the step falls through to the fused decode chunk — concurrent
    #: prompts neither serialize behind each other nor stall decode,
    #: and the per-bucket prefill/suffix programs are off the packed
    #: path (the warmup plan shrinks to one-or-two token-budget shapes
    #: plus the decode chunks). Off (default) preserves the bucketed
    #: path byte-for-byte. Incompatible with pipeline_decode and
    #: multi-host gangs; requests wanting prompt logprobs (echo) fall
    #: back to the bucketed prefill.
    packed_serving: bool = False
    #: Row capacity of the packed buffer; 0 = auto (max(256, enough for
    #: one decode row-block per slot plus one prefill block), rounded up
    #: to the RAGGED_BLOCK alignment).
    token_budget: int = 0

    @property
    def seq_len(self) -> int:
        return self.max_seq_len or self.model.max_seq_len

    @property
    def pages_per_seq(self) -> int:
        return -(-self.seq_len // self.page_size)

    @property
    def packed_token_budget(self) -> int:
        """The resolved [token_budget] buffer size: requested (or the
        auto default), rounded up to RAGGED_BLOCK alignment and floored
        so every slot can decode AND at least one prefill block always
        fits — a budget too small to carry the running batch would
        deadlock admission."""
        from ..ops.attention import RAGGED_BLOCK as qb

        want = self.token_budget or 256
        floor = qb * (self.max_batch + 1)
        want = max(want, floor)
        return -(-want // qb) * qb


def resolve_attention_impl(impl: str) -> str:
    if impl == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "grouped"
    return impl


def prefill_bucket(n: int, seq_len: int) -> int:
    """Power-of-two prefill shape bucket (floor 16) clamped to seq_len —
    the ONE definition shared by live dispatch and the AOT warmup plan
    (exec_pool.warmup_plan). They must agree bit-for-bit: a divergence
    would pool executables at buckets the dispatch never asks for, and
    every lookup would silently miss back to first-touch jit."""
    b = 16
    while b < n:
        b *= 2
    return min(b, seq_len)


def packed_budget_shapes(cfg: EngineConfig) -> List[int]:
    """The one-or-two compiled [token_budget] buffer shapes of a packed
    engine, smallest first: the full budget, preceded by a quarter-size
    buffer (when it usefully differs) so a lightly loaded step — one
    admission, a thin decode batch — neither computes nor pad-counts the
    full budget. The ONE definition shared by live dispatch and the AOT
    warmup plan (exec_pool.warmup_plan), like prefill_bucket above."""
    from ..ops.attention import RAGGED_BLOCK as qb

    full = cfg.packed_token_budget
    small = -(-max(full // 4, qb * (cfg.max_batch + 1)) // qb) * qb
    return [small, full] if small < full else [full]


def mixed_bucket(rows: int, kv_pages: int) -> int:
    """AOT/dispatch bucket id of one compiled mixed-program shape:
    (buffer rows, page-table width). The packed dispatch slices the page
    table to the power-of-two page count the step's longest sequence
    actually needs — BIT-EXACT (the sliced-away entries were hard-masked
    for every row, contributing exact fp32 zeros to the softmax), and it
    bounds the reference twin's O(rows * ctx) gather by live context
    instead of max_seq. Like prefill_bucket, at most log2(pages_per_seq)
    widths ever compile; the warmup plan covers the full width (always
    correct), narrower ones jit on first touch."""
    return (int(rows) << 16) | int(kv_pages)


def kv_pages_bucket(max_kv: int, page_size: int, pages_per_seq: int) -> int:
    """Page-table width covering `max_kv` cache entries, rounded up to
    the {1, 2, 3, 4, 6, 8, 12, ...} bucket ladder (powers of two and
    their 1.5x midpoints — halves the worst-case over-read vs plain
    pow2 at twice the compiled widths, still O(log) shapes), clamped to
    the full table."""
    need = max(1, -(-max_kv // page_size))
    k = 1
    while k < need:
        if k * 3 // 2 >= need and k * 3 % 2 == 0:
            k = k * 3 // 2
            break
        k *= 2
    return min(k, pages_per_seq)


@dataclass
class Request:
    seq_id: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    out_tokens: List[int] = field(default_factory=list)
    out_logprobs: List[float] = field(default_factory=list)
    #: per emitted token: [(token_id, logprob), ...] top-k alternatives of
    #: the raw distribution (filled only when `want_top_logprobs`)
    out_top_logprobs: List[list] = field(default_factory=list)
    #: materialize per-token alternatives on the host (the device always
    #: computes cfg.logprobs_topk; the Python tuple-building per token is
    #: what this gates — most requests never ask for logprobs)
    want_top_logprobs: bool = False
    #: per-request RNG seed (OpenAI/vLLM `seed`): with it, a sampled
    #: (temperature > 0) request's output depends only on (seed, params,
    #: prompt, sampling knobs) — not on batch composition or arrival
    #: order. None = a stream derived from the engine seed and seq_id.
    seed: Optional[int] = None
    #: OpenAI `logit_bias`: token_id -> additive logit bias in [-100,
    #: 100]; applied before temperature/top-p, shifts greedy too. Empty
    #: = off.
    logit_bias: Dict[int, float] = field(default_factory=dict)
    #: vLLM `ignore_eos`: decode the full token budget even when the
    #: model emits eos (benchmark harnesses need length-controlled runs)
    ignore_eos: bool = False
    #: OpenAI `echo` + `logprobs`: logprob of every PROMPT token under the
    #: model (first entry None — nothing precedes it). Requesting this
    #: bypasses the prefix cache: cached pages skip exactly the forward
    #: that would produce these numbers.
    want_prompt_logprobs: bool = False
    prompt_logprobs: List[Optional[float]] = field(default_factory=list)
    #: nucleus sampling threshold; >= 1.0 = full distribution
    top_p: float = 1.0
    #: OpenAI repetition penalties (0 = off); applied to logits before
    #: temperature/top-p over counts of prompt + generated tokens
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    #: stop sequences (token tuples); on match the request finishes and
    #: the matched sequence is stripped from the output (OpenAI semantics)
    stop_seqs: tuple = ()
    pages: List[int] = field(default_factory=list)
    pos: int = 0  # tokens in cache
    slot: int = -1
    #: prompt tokens served from the prefix cache (0 = full prefill)
    cached_tokens: int = 0
    #: how many of `pages` are shared prefix pages (for registration)
    shared_pages: int = 0
    done: bool = False
    #: why the request finished: "length" | "stop" (eos or stop sequence)
    finish_reason: str = ""
    error: Optional[str] = None
    submit_time: float = field(default_factory=time.monotonic)
    #: when the request first won a slot (_admit) — with submit_time this
    #: separates queue wait from prefill inside TTFT
    #: (fma_engine_queue_wait_seconds)
    first_sched_time: Optional[float] = None
    first_token_time: Optional[float] = None
    #: stamped by the serving loop when the finished request leaves the
    #: engine (SLO TPOT judgment + the usage block's decode_tpot_s)
    done_time: Optional[float] = None
    #: Streaming hook: called as on_token(req, token) for every emitted
    #: token, on the engine thread. Keep it cheap (enqueue, don't compute).
    #: Tokens that could be the start of a stop sequence are held back
    #: until disambiguated, so streamed output never contains stripped
    #: stop-sequence content (OpenAI semantics).
    on_token: Optional[Callable[["Request", int], None]] = None
    #: tokens already delivered to on_token (stop-prefix holdback cursor)
    streamed: int = 0
    #: external early-stop request (e.g. a stop STRING matched on decoded
    #: text in the server layer): the engine finishes the request at the
    #: next emitted token instead of decoding to eos/max_tokens
    stop_requested: bool = False
    #: packed serving: admitted but the prompt is not fully in cache yet
    #: (req.pos tracks progress); excluded from decode dispatch until the
    #: final prefill segment samples the first token
    prefilling: bool = False
    #: co-resident variant handle routing this request's forwards
    #: (InferenceEngine.attach_variant): 0 = the engine's base params.
    #: Routed requests require packed serving — the bucketed programs
    #: always run base params.
    variant: int = 0
    #: explicit [2] uint32 RNG key data seated instead of the derived
    #: key at admission. Set only on migrated-in requests: a seed-None
    #: request's key is derived from (engine seed, seq_id), both of
    #: which differ on the importing engine, so the exporter pins the
    #: exact key its own admission would have used.
    rng_key_data: Optional[Any] = None
    #: request-lifecycle trace collector (tracing.RequestTrace) or None.
    #: None — the --trace-requests 0 default — keeps every hook on the
    #: serving hot path to a single ``is None`` check.
    trace: Optional[Any] = None
    #: stamped when the trace is finished, so the serving layer's usage
    #: block can surface it after the collector is gone
    trace_id: str = ""
    #: total wall time this request spent preempted (parked + the park /
    #: resume transfers themselves), and the share of it that happened
    #: before the first token — the leg accounting that keeps
    #: queue/prefill/decode legs a partition of submit→done
    preempt_s: float = 0.0
    preempt_pre_token_s: float = 0.0
    #: migrated-in requests: origin trace context ({"trace_id","span_id"})
    #: decoded from the parked bundle, so destination spans join the SAME
    #: trace the source started
    trace_parent: Optional[dict] = None


def validate_logit_bias(lb, vocab_size: int) -> "Dict[int, float] | None":
    """OpenAI logit_bias validation, shared by the HTTP layer (-> 400)
    and add_request (-> per-request error): token ids must be in-vocab,
    values in [-100, 100]. Returns a normalized {int: float} dict."""
    if lb is None:
        return None
    if not isinstance(lb, dict):
        raise ValueError("logit_bias must be an object")
    out: Dict[int, float] = {}
    for k, v in lb.items():
        try:
            t = int(k)
            fv = float(v)
        except (TypeError, ValueError):
            raise ValueError(f"invalid logit_bias entry {k!r}: {v!r}")
        if not (0 <= t < vocab_size):
            raise ValueError(f"logit_bias token {t} outside vocab")
        if not (-100.0 <= fv <= 100.0):
            raise ValueError(f"logit_bias value {fv} outside [-100, 100]")
        out[t] = fv
    return out


def _stop_holdback(out: List[int], stop_seqs) -> int:
    """Length of the longest suffix of `out` that is a PROPER prefix of
    any stop sequence — tokens that must not be streamed yet because the
    next tokens may complete a stop match (and the whole match is then
    stripped from the output)."""
    best = 0
    for seq in stop_seqs:
        m = min(len(seq) - 1, len(out))
        for k in range(m, best, -1):
            if tuple(out[-k:]) == tuple(seq[:k]):
                best = k
                break
    return best


def _copy_node(node):
    if isinstance(node, dict):
        return dict(node)
    if isinstance(node, (list, tuple)):
        return list(node)
    raise TypeError(f"not an interior pytree node: {type(node)!r}")


def _leaf_at(params: Any, key: str) -> Any:
    """Resolve a flat '/'-joined leaf key (the chunk_store digest-map
    convention) inside a nested param tree. Raises KeyError/IndexError/
    TypeError when the path does not lead to a leaf."""
    node = params
    for p in key.split("/"):
        node = node[int(p)] if isinstance(node, (list, tuple)) else node[p]
    return node


def _subst_leaves(params: Any, delta: Dict[str, Any]) -> Any:
    """Copy-on-write substitution of flat-keyed leaves into a nested
    param tree: the returned tree aliases every untouched subtree of
    ``params``, so tracing one variant pass per co-resident sibling
    references each shared base tensor ONCE — the in-program half of
    the HBM dedup (attach_variant holds only changed leaves on
    device)."""
    root = _copy_node(params)
    for key, leaf in delta.items():
        parts = key.split("/")
        node = root
        for p in parts[:-1]:
            idx = int(p) if isinstance(node, list) else p
            child = _copy_node(node[idx])
            node[idx] = child
            node = child
        last = parts[-1]
        node[int(last) if isinstance(node, list) else last] = leaf
    return root


class EngineAsleep(RuntimeError):
    """The engine's device state is offloaded; wake_up() before serving."""


class ProgramSet:
    """The engine's compiled-program surface, built from static config only
    (model config + sampling/eos scalars) — no params, no device state.

    This is what lets the AOT warmup driver (engine/exec_pool.py) construct
    and compile the serving programs for a model that is not resident yet,
    while its weights are still streaming host->device: ``jax.jit`` only
    needs the traced function and abstract avals, so compilation is pure
    host-CPU work that overlaps cleanly with the transfer DMA.

    The engine owns one ProgramSet; the warmup driver builds its own for
    the incoming config and hands the resulting executables over through
    ``InferenceEngine.install_executable`` — jit caches are keyed by
    function identity, so the *executable*, not the jitted wrapper, is the
    unit that crosses between them.
    """

    def __init__(
        self,
        model_cfg,
        logprobs_topk: int,
        eos_token_id: int,
        mixed_impl: Optional[str] = None,
        mesh: Optional[Mesh] = None,
    ) -> None:
        self.model_cfg = model_cfg
        self.alt_k = int(logprobs_topk)
        self.eos = int(eos_token_id)
        #: attention impl override for the MIXED program only (the
        #: routing matrix of device kind x mesh x impl flag —
        #: ops/attention.py:resolve_ragged_impl: pallas engines keep
        #: the kernel on meshes via its shard_map port, non-pallas and
        #: interpret-incapable CPU meshes run the XLA twin); None =
        #: model config's
        self.mixed_impl = mixed_impl
        #: the engine's mesh: device-RESIDENT scheduler outputs (counts,
        #: bias, last tokens, ...) are pinned replicated on it so their
        #: sharding is a fixed point across dispatches — without the pin
        #: GSPMD shards them however the program liked (e.g. counts over
        #: the tp vocab axis), the next dispatch's input sharding drifts
        #: from the uploaded/compiled one, and every AOT executable
        #: mismatches after its first call
        self.mesh = mesh
        self.prefill = jax.jit(self._make_prefill(False), donate_argnums=(3,))
        self.prefill_plp = jax.jit(self._make_prefill(True), donate_argnums=(3,))
        self.suffix = jax.jit(
            self._make_suffix_prefill(False), donate_argnums=(5,)
        )
        self.suffix_plp = jax.jit(
            self._make_suffix_prefill(True), donate_argnums=(5,)
        )
        self.verify = jax.jit(self._make_verify(), donate_argnums=(4,))
        #: the token-packed mixed-batch programs, one jitted function per
        #: page-table slice width (mixed(kvp), like chunk(T)): jit then
        #: specializes per buffer shape — two budget shapes
        #: (packed_budget_shapes) x O(log) KV widths (kv_pages_bucket)
        #: ever dispatch, and the AOT warmup covers the two full-width
        #: shapes (exec_pool.warmup_plan)
        self._mixed: Dict[int, Any] = {}
        self._chunks: Dict[int, Any] = {}
        #: multi-variant twins of the mixed/chunk programs (co-resident
        #: sibling serving, InferenceEngine.attach_variant): same
        #: per-width/per-T cache discipline; jit additionally retraces
        #: per delta-pytree structure (the resident set is an argument).
        #: Never AOT-pooled — whenever no routed request is live the
        #: dispatchers fall back to the plain programs above, so the
        #: warmed executables keep serving base-only traffic untouched.
        self._mixed_multi: Dict[int, Any] = {}
        self._chunks_multi: Dict[int, Any] = {}

    def _pin_resident(self, *xs):
        """Constrain device-resident scheduler outputs to the replicated
        sharding the engine uploads them with (no-op off-mesh): state
        that round-trips through dispatches must keep a stable sharding
        or AOT executables mismatch after one call (see __init__)."""
        if self.mesh is None:
            return xs if len(xs) > 1 else xs[0]
        from jax.sharding import NamedSharding, PartitionSpec

        sh = NamedSharding(self.mesh, PartitionSpec())
        pinned = tuple(
            jax.lax.with_sharding_constraint(x, sh) for x in xs
        )
        return pinned if len(pinned) > 1 else pinned[0]

    # -- shared program tails -------------------------------------------------

    def _sample_last(
        self, logits, lens, temp, topp, counts, pres, freq, skey, bias
    ):
        """Shared sampling tail of both prefill programs: take the last
        valid logit, split the request's OWN key, sample — one definition
        so the cache-hit path can never diverge from the cold one."""
        alt_k = self.alt_k
        last = jnp.take_along_axis(
            logits, (lens - 1)[:, None, None], axis=1
        )[:, 0]
        key = jax.random.wrap_key_data(skey)
        key, sub = jax.random.split(key)
        out = sample(
            last, sub, temp, top_p=topp,
            counts=counts, presence_penalty=pres, frequency_penalty=freq,
            alt_k=alt_k, bias=bias,
        )
        tok, lp = out[0], out[1]
        alts = out[2:] if alt_k > 0 else (
            jnp.zeros((tok.shape[0], 0), jnp.float32),
            jnp.zeros((tok.shape[0], 0), jnp.int32),
        )
        return tok, lp, alts[0], alts[1], jax.random.key_data(key)

    @staticmethod
    def _prompt_lps(logits, targets):
        """Per-position logprob of `targets` (the NEXT prompt token at
        each position) under the model — OpenAI echo+logprobs."""
        norm = logits - jax.scipy.special.logsumexp(
            logits, axis=-1, keepdims=True
        )
        return jnp.take_along_axis(
            norm, targets[..., None], axis=-1
        )[..., 0]

    # -- program factories ----------------------------------------------------

    def _make_prefill(self, with_plp: bool):
        """Two compiled variants: prompt-logprob scoring is an extra
        vocab-wide logsumexp over the WHOLE bucket — only echo requests
        pay for it. Signatures match, so call sites just pick the
        function."""
        model_cfg = self.model_cfg

        def _prefill(
            params, tokens, seq_lens, cache, page_table, temp, topp,
            counts, pres, freq, skey, bias,
        ):
            logits, cache = llama.prefill(
                params, model_cfg, tokens, seq_lens, cache, page_table
            )
            tok, lp, av, ai, skey = self._sample_last(
                logits, seq_lens, temp, topp, counts, pres, freq, skey,
                bias,
            )
            if with_plp:
                # position i predicts token i+1: shift the prompt left
                targets = jnp.roll(tokens, -1, axis=1)
                plp = self._prompt_lps(logits, targets)
            else:
                plp = jnp.zeros(tokens.shape, jnp.float32)
            return tok, lp, av, ai, plp, cache, skey

        return _prefill

    def _make_suffix_prefill(self, with_plp: bool):
        model_cfg = self.model_cfg

        def _suffix_prefill(
            params, tokens, targets, start, suffix_lens, cache,
            page_table, temp, topp, counts, pres, freq, skey, bias,
        ):
            logits, cache = llama.prefill_continue(
                params, model_cfg, tokens, start, suffix_lens, cache,
                page_table,
            )
            tok, lp, av, ai, skey = self._sample_last(
                logits, suffix_lens, temp, topp, counts, pres, freq,
                skey, bias,
            )
            if with_plp:
                # a segment cannot derive its last target (the NEXT
                # segment's first token) from its own tokens, so
                # targets come in
                plp = self._prompt_lps(logits, targets)
            else:
                plp = jnp.zeros(tokens.shape, jnp.float32)
            return tok, lp, av, ai, plp, cache, skey

        return _suffix_prefill

    def _make_verify(self):
        model_cfg = self.model_cfg
        alt_k = self.alt_k

        def _verify(params, tokens, start, window_len, cache, page_table):
            """Speculative verify: run the window [last_token, q1..q_{k-1}]
            through the continue program and return the model's GREEDY next
            token at every window position, with its logprob (the logprobs
            API must not degrade under speculation)."""
            logits, cache = llama.prefill_continue(
                params, model_cfg, tokens, start, window_len, cache,
                page_table,
            )
            norm = logits - jax.scipy.special.logsumexp(
                logits, axis=-1, keepdims=True
            )
            toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            lps = jnp.take_along_axis(norm, toks[..., None], axis=-1)[..., 0]
            if alt_k > 0:
                avs, ais = jax.lax.top_k(norm, alt_k)
            else:
                b, w = toks.shape
                avs = jnp.zeros((b, w, 0), jnp.float32)
                ais = jnp.zeros((b, w, 0), jnp.int32)
            return toks, lps, avs, ais.astype(jnp.int32), cache

        return _verify

    def _make_mixed(self, kvp: int):
        """The token-packed mixed-batch program: one forward over a flat
        [token_budget] buffer (llama.mixed_step), then the shared
        sampling tail over ONE gathered row per slot — each sequence
        emits at most one token per packed step (a prefill segment's
        first token or a decode step), so the in-program budget/eos
        machinery of the chunk program is unnecessary; the host applies
        it between steps exactly like the bucketed prefill path.

        Scheduler state is DEVICE-RESIDENT, chunk-program style: the
        [b, vocab] token counts and logit-bias mirrors arrive as device
        arrays (donated) and the program maintains them itself —
        ``fresh_on`` slots (admitted this step with no exact-count /
        bias edge) zero their rows, ``count_row`` rows (streamed prompt
        tokens) join their slot's counts BEFORE the sampling tail (so
        the final segment's sample sees the whole prompt, exactly like
        the bucketed prefill's counts row), and each sampling slot's
        emitted token joins the counts after (the chunk program's
        post-sample add). The full-width page table is device-resident
        too; the program slices it to this function's static ``kvp``
        width (bit-exact: the sliced-away entries were hard-masked
        exact zeros). Steady-state per-step H2D is therefore O(rows) —
        the [b, vocab] mirrors re-upload only on dirty edges."""
        model_cfg = self.model_cfg
        if self.mixed_impl and model_cfg.attention_impl != self.mixed_impl:
            import dataclasses

            model_cfg = dataclasses.replace(
                model_cfg, attention_impl=self.mixed_impl
            )
        alt_k = self.alt_k

        def _mixed(
            params, tokens, row_slot, positions, count_row, sample_rows,
            sample_on, fresh_on, cache, page_table, temps, topps, counts,
            pres, freq, skeys, bias,
        ):
            b = sample_rows.shape[0]
            pt = jax.lax.slice_in_dim(page_table, 0, kvp, axis=1)
            # device-side dirty-edge maintenance: a freshly admitted
            # slot's rows still hold the previous occupant's state —
            # zero them here instead of re-uploading [b, vocab] mirrors
            fresh = fresh_on > 0
            counts = jnp.where(fresh[:, None], 0, counts)
            bias = jnp.where(fresh[:, None], 0.0, bias)
            # streamed prompt rows join their slot's counts BEFORE the
            # sample (penalties see the full prompt at the final
            # segment); padding / decode rows scatter out of bounds
            add_slot = jnp.where(count_row > 0, row_slot, b)
            counts = counts.at[add_slot, tokens].add(1, mode="drop")
            logits, cache = llama.mixed_step(
                params, model_cfg, tokens, row_slot, positions, cache,
                pt, mesh=self.mesh,
            )
            last = logits[sample_rows]  # [b, vocab]
            # per-slot key split, advanced only for slots that sample this
            # step (same discipline as the chunk program's active mask):
            # a request's draw count stays a function of its own progress
            keys = jax.random.wrap_key_data(skeys)
            pairs = jax.vmap(jax.random.split)(keys)  # [b, 2]
            subs = pairs[:, 1]
            new_data = jax.random.key_data(pairs[:, 0])
            active = sample_on > 0
            skeys = jnp.where(active[:, None], new_data, skeys)
            out = sample(
                last, subs, temps, top_p=topps,
                counts=counts, presence_penalty=pres,
                frequency_penalty=freq, alt_k=alt_k, bias=bias,
            )
            tok, lp = out[0], out[1]
            if alt_k > 0:
                av, ai = out[2], out[3]
            else:
                av = jnp.zeros((tok.shape[0], 0), jnp.float32)
                ai = jnp.zeros((tok.shape[0], 0), jnp.int32)
            # the emitted token joins the counts the NEXT step penalizes
            # (host _emit mirrors the same add)
            counts = counts.at[jnp.arange(b), tok].add(
                active.astype(jnp.int32)
            )
            counts, bias = self._pin_resident(counts, bias)
            return tok, lp, av, ai, cache, counts, bias, skeys

        return _mixed

    def _make_mixed_multi(self, kvp: int):
        """Multi-variant twin of :meth:`_make_mixed` (co-resident sibling
        serving): V = 1 + len(deltas) unrolled forward passes over the
        same packed buffer — pass 0 with the base params, pass v with
        variant v's delta leaves substituted copy-on-write
        (:func:`_subst_leaves`: every shared base tensor is referenced,
        never duplicated). Pass v masks the buffer to its own rows
        (``row_slot`` forced to -1 elsewhere — a masked row is exactly a
        padding row: computed, never scattered into the KV pool), so
        each row's KV is written exactly once, by its own variant's
        weights, and per-row outputs match a solo dispatch of that
        variant bit-for-bit (batch-composition invariance is the packed
        path's existing contract). Logits merge row-wise by variant
        index and the sampling tail runs ONCE on the merged logits, so
        the per-slot RNG/count/bias discipline is identical to the plain
        program."""
        model_cfg = self.model_cfg
        if self.mixed_impl and model_cfg.attention_impl != self.mixed_impl:
            import dataclasses

            model_cfg = dataclasses.replace(
                model_cfg, attention_impl=self.mixed_impl
            )
        alt_k = self.alt_k

        def _mixed_multi(
            params, deltas, tok_variant, tokens, row_slot, positions,
            count_row, sample_rows, sample_on, fresh_on, cache,
            page_table, temps, topps, counts, pres, freq, skeys, bias,
        ):
            b = sample_rows.shape[0]
            pt = jax.lax.slice_in_dim(page_table, 0, kvp, axis=1)
            fresh = fresh_on > 0
            counts = jnp.where(fresh[:, None], 0, counts)
            bias = jnp.where(fresh[:, None], 0.0, bias)
            add_slot = jnp.where(count_row > 0, row_slot, b)
            counts = counts.at[add_slot, tokens].add(1, mode="drop")
            logits = None
            for v in range(len(deltas) + 1):
                p_v = (
                    params if v == 0
                    else _subst_leaves(params, deltas[v - 1])
                )
                mine = tok_variant == v
                rs_v = jnp.where(mine, row_slot, -1)
                lg, cache = llama.mixed_step(
                    p_v, model_cfg, tokens, rs_v, positions, cache,
                    pt, mesh=self.mesh,
                )
                logits = (
                    lg if logits is None
                    else jnp.where(mine[:, None], lg, logits)
                )
            last = logits[sample_rows]  # [b, vocab]
            keys = jax.random.wrap_key_data(skeys)
            pairs = jax.vmap(jax.random.split)(keys)  # [b, 2]
            subs = pairs[:, 1]
            new_data = jax.random.key_data(pairs[:, 0])
            active = sample_on > 0
            skeys = jnp.where(active[:, None], new_data, skeys)
            out = sample(
                last, subs, temps, top_p=topps,
                counts=counts, presence_penalty=pres,
                frequency_penalty=freq, alt_k=alt_k, bias=bias,
            )
            tok, lp = out[0], out[1]
            if alt_k > 0:
                av, ai = out[2], out[3]
            else:
                av = jnp.zeros((tok.shape[0], 0), jnp.float32)
                ai = jnp.zeros((tok.shape[0], 0), jnp.int32)
            counts = counts.at[jnp.arange(b), tok].add(
                active.astype(jnp.int32)
            )
            counts, bias = self._pin_resident(counts, bias)
            return tok, lp, av, ai, cache, counts, bias, skeys

        return _mixed_multi

    def _make_chunk_multi(self, T: int):
        """Multi-variant twin of :meth:`_make_chunk`: per fused step, one
        decode pass per resident set member with the active mask
        narrowed to that member's slots (an inactive row's KV write
        drops inside llama.decode_step), logits merged by slot variant,
        then the one shared sampling tail — so routed and base slots
        decode bit-identically to their solo runs while sharing the
        dispatch."""
        model_cfg = self.model_cfg
        eos = self.eos
        alt_k = self.alt_k

        def chunk_multi(
            params, deltas, slot_variant, lt, pos, budget, cache,
            page_table, temps, topps, counts, pres, freq, skeys, eos_on,
            bias,
        ):
            trees = [params] + [_subst_leaves(params, d) for d in deltas]

            def body(carry, _):
                lt, pos, budget, cache, counts, skeys = carry
                active = budget > 0
                logits = None
                for v, p_v in enumerate(trees):
                    mine = slot_variant == v
                    lg, cache = llama.decode_step(
                        p_v, model_cfg, lt, pos, cache, page_table,
                        active & mine,
                    )
                    logits = (
                        lg if logits is None
                        else jnp.where(mine[:, None], lg, logits)
                    )
                keys = jax.random.wrap_key_data(skeys)  # [b] typed keys
                pairs = jax.vmap(jax.random.split)(keys)  # [b, 2]
                subs = pairs[:, 1]
                new_data = jax.random.key_data(pairs[:, 0])
                skeys = jnp.where(active[:, None], new_data, skeys)
                out = sample(
                    logits, subs, temps, top_p=topps,
                    counts=counts, presence_penalty=pres,
                    frequency_penalty=freq,
                    alt_k=alt_k, bias=bias,
                )
                nxt, lp = out[0], out[1]
                if alt_k > 0:
                    av, ai = out[2], out[3]
                else:
                    av = jnp.zeros((nxt.shape[0], 0), jnp.float32)
                    ai = jnp.zeros((nxt.shape[0], 0), jnp.int32)
                nxt = jnp.where(active, nxt, lt)
                a32 = active.astype(jnp.int32)
                counts = counts.at[jnp.arange(counts.shape[0]), nxt].add(a32)
                pos = pos + a32
                budget = budget - a32
                if eos >= 0:
                    budget = jnp.where(
                        active & (nxt == eos) & (eos_on > 0), 0, budget
                    )
                return (
                    (nxt, pos, budget, cache, counts, skeys),
                    (nxt, lp, av, ai),
                )

            (
                (lt, pos, budget, cache, counts, skeys),
                (toks, lps, avs, ais),
            ) = jax.lax.scan(
                body, (lt, pos, budget, cache, counts, skeys), None, length=T
            )
            lt, pos, budget, counts, skeys = self._pin_resident(
                lt, pos, budget, counts, skeys
            )
            return (
                toks, lps, avs, ais, lt, pos, budget, cache, counts, skeys,
            )

        return chunk_multi

    def _make_chunk(self, T: int):
        model_cfg = self.model_cfg
        eos = self.eos
        alt_k = self.alt_k

        def chunk(
            params, lt, pos, budget, cache, page_table, temps, topps,
            counts, pres, freq, skeys, eos_on, bias,
        ):
            def body(carry, _):
                lt, pos, budget, cache, counts, skeys = carry
                active = budget > 0
                logits, cache = llama.decode_step(
                    params, model_cfg, lt, pos, cache, page_table, active
                )
                # each slot splits its OWN key — and only while active, so
                # a request's draw count is a function of its own progress,
                # not of how long it shared the batch with others
                keys = jax.random.wrap_key_data(skeys)  # [b] typed keys
                pairs = jax.vmap(jax.random.split)(keys)  # [b, 2]
                subs = pairs[:, 1]
                new_data = jax.random.key_data(pairs[:, 0])
                skeys = jnp.where(active[:, None], new_data, skeys)
                out = sample(
                    logits, subs, temps, top_p=topps,
                    counts=counts, presence_penalty=pres,
                    frequency_penalty=freq,
                    alt_k=alt_k, bias=bias,
                )
                nxt, lp = out[0], out[1]
                if alt_k > 0:
                    av, ai = out[2], out[3]
                else:
                    av = jnp.zeros((nxt.shape[0], 0), jnp.float32)
                    ai = jnp.zeros((nxt.shape[0], 0), jnp.int32)
                nxt = jnp.where(active, nxt, lt)
                a32 = active.astype(jnp.int32)
                # the emitted token joins the counts the NEXT step penalizes
                counts = counts.at[jnp.arange(counts.shape[0]), nxt].add(a32)
                pos = pos + a32
                budget = budget - a32
                if eos >= 0:
                    budget = jnp.where(
                        active & (nxt == eos) & (eos_on > 0), 0, budget
                    )
                return (
                    (nxt, pos, budget, cache, counts, skeys),
                    (nxt, lp, av, ai),
                )

            (
                (lt, pos, budget, cache, counts, skeys),
                (toks, lps, avs, ais),
            ) = jax.lax.scan(
                body, (lt, pos, budget, cache, counts, skeys), None, length=T
            )
            lt, pos, budget, counts, skeys = self._pin_resident(
                lt, pos, budget, counts, skeys
            )
            return (
                toks, lps, avs, ais, lt, pos, budget, cache, counts, skeys,
            )

        return chunk

    def chunk(self, T: int):
        """The jitted T-step decode chunk (cached per T). At most two ever
        compile in serving (T = decode_chunk and T = 1) — compiles are
        expensive on TPU."""
        fn = self._chunks.get(T)
        if fn is None:
            # donate scheduler state + cache + counts + key data
            fn = self._chunks[T] = jax.jit(
                self._make_chunk(T), donate_argnums=(1, 2, 3, 4, 8, 11)
            )
        return fn

    def mixed(self, kvp: int):
        """The jitted mixed-batch program at page-table slice width
        `kvp` (cached per width, like chunk(T)): the slice width is a
        closure constant, so the jit specializes per (buffer shape, kvp)
        exactly as the old host-sliced dispatch did — same compile
        count, but the full-width table stays device-resident."""
        fn = self._mixed.get(kvp)
        if fn is None:
            # donate cache + the device-resident counts/bias mirrors
            fn = self._mixed[kvp] = jax.jit(
                self._make_mixed(kvp), donate_argnums=(8, 12, 16)
            )
        return fn

    def mixed_multi(self, kvp: int):
        """The jitted multi-variant mixed program at page-table width
        ``kvp`` — dispatched instead of :meth:`mixed` only on steps
        whose buffer carries at least one routed row."""
        fn = self._mixed_multi.get(kvp)
        if fn is None:
            # same donation set as mixed(), shifted by the two leading
            # read-only variant args (deltas, tok_variant)
            fn = self._mixed_multi[kvp] = jax.jit(
                self._make_mixed_multi(kvp), donate_argnums=(10, 14, 18)
            )
        return fn

    def chunk_multi(self, T: int):
        """The jitted multi-variant T-step decode chunk — dispatched
        instead of :meth:`chunk` only while a routed request occupies a
        decodable slot."""
        fn = self._chunks_multi.get(T)
        if fn is None:
            # chunk()'s donation set shifted by (deltas, slot_variant)
            fn = self._chunks_multi[T] = jax.jit(
                self._make_chunk_multi(T),
                donate_argnums=(3, 4, 5, 6, 10, 13),
            )
        return fn


class InferenceEngine:
    def __init__(
        self,
        cfg: EngineConfig,
        params: Optional[Dict[str, Any]] = None,
        mesh: Optional[Mesh] = None,
        seed: int = 0,
    ) -> None:
        impl = resolve_attention_impl(cfg.attention_impl)
        self.cfg = cfg
        self.mesh = mesh
        # thread the attention impl through the model config (per-engine, not
        # a process global — two engines must not clobber each other)
        m = cfg.model
        if m.attention_impl != impl:
            import dataclasses

            m = dataclasses.replace(m, attention_impl=impl)
        if params is None:
            from ..models.registry import init_params_for

            params = init_params_for(jax.random.key(seed), m)
        if mesh is not None:
            from ..models.registry import logical_axes_for

            params = shard_pytree(params, mesh, logical_axes_for(m))
        else:
            # Commit to the default device: committed-ness is part of the jit
            # cache key, and the post-wake device_put restore produces
            # committed arrays — starting committed keeps one compiled set.
            params = jax.device_put(params, jax.devices()[0])
        self.params = params
        self.pool = PagePool.create(
            m.num_layers,
            cfg.num_pages,
            cfg.page_size,
            m.num_kv_heads,
            m.head_dim,
            dtype=m.dtype,
            mesh=mesh,
        )
        if mesh is None:
            self.pool.replace(
                jax.device_put(self.pool.as_tuple(), jax.devices()[0])
            )
        self.allocator = PageAllocator(cfg.num_pages)
        if cfg.prefix_caching:
            from .prefix_cache import PrefixCache

            self.prefix_cache: Optional[Any] = PrefixCache(cfg.page_size)
        else:
            self.prefix_cache = None
        b, p = cfg.max_batch, cfg.pages_per_seq
        # Host mirrors of the device scheduler state (source of truth between
        # chunks; re-uploaded only after an admission/retire/prefill edge).
        self._page_table = np.zeros((b, p), dtype=np.int32)
        self._positions = np.zeros((b,), dtype=np.int32)
        self._last_tokens = np.zeros((b,), dtype=np.int32)
        self._temps = np.zeros((b,), dtype=np.float32)
        self._topps = np.ones((b,), dtype=np.float32)
        self._pres = np.zeros((b,), dtype=np.float32)
        self._freqs = np.zeros((b,), dtype=np.float32)
        #: per-slot token counts over prompt + generated (penalties input);
        #: host-exact mirror of the device copy the chunk program maintains
        self._token_counts = np.zeros((b, cfg.model.vocab_size), dtype=np.int32)
        self._budgets = np.zeros((b,), dtype=np.int32)
        #: per-slot eos sensitivity (0 = ignore_eos request): the chunk
        #: program zeroes a slot's budget at eos only when enabled
        self._eos_on = np.ones((b,), dtype=np.int32)
        #: per-slot additive logit bias [b, vocab] (OpenAI logit_bias);
        #: zero rows for requests without one
        self._bias = np.zeros((b, cfg.model.vocab_size), dtype=np.float32)
        self._slots: List[Optional[Request]] = [None] * b
        self._waiting: List[Request] = []
        self._next_seq_id = 1
        #: lifetime emitted-token count (observability; lets tests assert
        #: that early stopping really saved decode work)
        self.total_tokens_emitted = 0
        self._seed = seed
        #: per-slot RNG key data [b, 2]: every slot samples from its OWN
        #: key stream (seeded requests get key(seed); unseeded get a
        #: fold_in of the engine seed and their seq_id), so a seeded
        #: request's draws are independent of batch neighbors. The host
        #: mirror re-syncs from the device after every chunk.
        self._slot_keys = np.zeros((b, 2), dtype=np.uint32)
        self._dev: Optional[Dict[str, Any]] = None  # device scheduler arrays
        self._dirty = True
        #: Multi-host lockstep (engine/multihost.py): the gang leader's
        #: engine broadcasts a control frame before every compiled dispatch
        #: so follower processes replay the identical program. None when
        #: single-host or follower.
        self.lockstep: Optional[Any] = None

        self._model_cfg = m

        # One ProgramSet per engine (jit caches key on function identity,
        # so two engines never share a cache); the flat _*_fn attributes
        # keep the historical names the lockstep follower replays through.
        from ..ops.attention import resolve_ragged_impl

        self.programs = ProgramSet(
            m, cfg.logprobs_topk, cfg.eos_token_id,
            mixed_impl=resolve_ragged_impl(impl, mesh),
            mesh=mesh,
        )
        self._prefill_fn = self.programs.prefill
        self._prefill_plp_fn = self.programs.prefill_plp
        self._suffix_prefill_fn = self.programs.suffix
        self._suffix_prefill_plp_fn = self.programs.suffix_plp
        self._verify_fn = self.programs.verify
        self._jit_programs = {
            "prefill": self.programs.prefill,
            "prefill_plp": self.programs.prefill_plp,
            "suffix": self.programs.suffix,
            "suffix_plp": self.programs.suffix_plp,
        }
        #: AOT-warmed executables keyed by (program, shape bucket / chunk
        #: T), installed by the exec-pool warmup driver; dispatch prefers
        #: them, a missing entry just means first-touch jit compile
        self._aot: Dict[Tuple[str, int], Any] = {}
        #: speculative decoding counters (observability)
        self.spec_proposed = 0
        self.spec_accepted = 0
        self._spec_miss_streak = 0
        self._spec_cooldown = 0
        # resolve the drain-tail policy once (mirrors
        # resolve_attention_impl): a typo must fail loudly, not silently
        # behave as "single"
        dt = cfg.drain_tail
        if dt == "auto":
            dt = "chunk" if jax.default_backend() == "tpu" else "single"
        if dt not in ("single", "chunk"):
            raise ValueError(
                f"drain_tail must be auto|single|chunk, got {dt!r}"
            )
        self._drain_tail_chunk = dt == "chunk"
        #: pipelined decode: the dispatched-but-unread chunk, and requests
        #: whose retire awaits that chunk's completion (see _defer_retire)
        self._inflight: Optional[tuple] = None
        self._pending_retire: List[Request] = []
        #: finished outside a step() call (drain_inflight before sleep):
        #: handed back by the next step() so the service resolves futures
        self._orphan_finished: List[Request] = []
        #: zero-drain actuation (engine/parked.py): True while the KV
        #: pool's device arrays were dropped by park_requests — the
        #: sleeper's state then excludes the pool, and set_state rebuilds
        #: a fresh one (rebuild_kv_pool) on restore
        self.kv_detached = False
        #: set by the service when --zero-drain applies to this engine:
        #: pricing peeks (plan_swap, _offload_wire_bytes) then size the
        #: offload WITHOUT the KV pool — matching what the actual
        #: park-then-offload will move
        self.zero_drain_park = False
        # -- token-packed mixed-batch serving (cfg.packed_serving) ----------
        self._packed = bool(cfg.packed_serving)
        if self._packed and cfg.pipeline_decode:
            # a packed step would race the in-flight chunk for the same
            # slots; the packed path already hides prefill behind decode
            raise ValueError(
                "packed_serving is incompatible with pipeline_decode"
            )
        self._token_budget = cfg.packed_token_budget if self._packed else 0
        #: packing alignment: the Pallas ragged kernel requires each
        #: sequence's run of rows to start on a RAGGED_BLOCK boundary
        #: (a kernel block holds one sequence) — on meshes too, where
        #: each shard_map shard replays the same block metadata over
        #: its head slice (resolve_ragged_impl). The XLA twin computes
        #: every row independently, so engines resolved to a non-pallas
        #: impl pack DENSELY: same outputs bit-for-bit, fewer padded
        #: rows
        from ..ops.attention import RAGGED_BLOCK

        self._pack_align = (
            RAGGED_BLOCK if resolve_ragged_impl(impl, mesh) == "pallas"
            else 1
        )
        #: packed engines track a second, cheaper staleness tier: the
        #: small per-slot mirrors (last tokens, positions, budgets, page
        #: table, temps/top-p/penalties, keys, eos) changed host-side but
        #: the [b, vocab] counts/bias device state is still exact — the
        #: next dispatch refreshes ONLY the small arrays
        #: (_upload_sched_rows, O(b·pages_per_seq) bytes) instead of the
        #: O(b·vocab) full re-upload. Bucketed engines never set it.
        self._rows_stale = False
        #: slots admitted by the packed path whose device counts/bias
        #: rows still hold the previous occupant's state: the next mixed
        #: dispatch zeroes them in-program (fresh_on); a full mirror
        #: upload makes the zeroing moot and clears the set
        self._fresh_slots: set = set()
        #: cumulative host->device scheduler/dispatch bytes per serving
        #: path (fma_engine_step_h2d_bytes_total; the decode bench's
        #: step_h2d_bytes_per_tok): "packed" counts mixed-program inputs
        #: plus every scheduler upload of a packed engine, "bucketed"
        #: counts the bucketed prefill/suffix/spec dispatch inputs and a
        #: bucketed engine's scheduler uploads
        self.step_h2d_bytes: Dict[str, int] = {"packed": 0, "bucketed": 0}
        #: bytes per padded activation row (pad-waste accounting):
        #: one embedding row of the model dtype
        self._pad_token_bytes = m.hidden_size * jnp.dtype(m.dtype).itemsize
        #: cumulative activation-padding waste per dispatch path, in
        #: bytes (fma_engine_prefill_pad_waste_bytes_total): "bucketed"
        #: counts power-of-two prefill bucket padding, "packed" counts
        #: every computed-but-invalid row of the mixed buffer
        self.pad_waste_bytes: Dict[str, int] = {"packed": 0, "bucketed": 0}
        #: valid-token accounting mirrors for the same two paths (the
        #: bench's pad_waste_frac denominators)
        self.dispatch_tokens: Dict[str, int] = {"packed": 0, "bucketed": 0}
        #: packed-step lifetime counters (observability / bench)
        self.packed_steps = 0
        self.packed_tokens_total = 0
        #: per-step stats of the most recent step() (None when the step
        #: did not dispatch the packed program) — the service mirrors
        #: these into the packed histogram/occupancy metrics and span
        self.last_step_stats: Optional[Dict[str, Any]] = None
        # -- co-resident sibling variants (attach_variant) ------------------
        #: variant handle -> {"delta": {flat_key: device leaf}, "nbytes",
        #: "label"}: per-variant changed leaves, already device-resident
        #: (device_put at attach is the ONLY H2D a sibling ever pays —
        #: shared base tensors are the live self.params, held once).
        #: Handle 0 is implicitly the base params and never appears here.
        #: Handles are STABLE for a variant's lifetime: requests and the
        #: service registry hold handles, and a detach re-derives the
        #: dense dispatch order instead of renumbering anything in
        #: flight.
        self._variants: Dict[int, Dict[str, Any]] = {}
        #: dense dispatch order: _variant_order[v-1] is the handle whose
        #: delta rides pass v of the multi programs
        self._variant_order: List[int] = []
        self._next_variant_handle = 1
        #: lifetime counters (observability / the coresident flight
        #: recorder records)
        self.variant_attaches = 0
        self.variant_detaches = 0

    # -- compiled-program dispatch (AOT executables > lazy jit) --------------

    def install_executable(self, program: str, bucket: int, compiled: Any) -> None:
        """Adopt an AOT-compiled executable for (program, shape bucket /
        chunk T) — the exec-pool warmup's delivery point (engine/
        exec_pool.py). Dispatch prefers installed executables; a missing
        entry just means first-touch jit compile, exactly as before."""
        self._aot[(program, int(bucket))] = compiled

    def clear_executables(self) -> None:
        """Forget installed AOT executables. Device release destroys the
        PJRT client that owns them; the service re-validates pool entries
        (or recompiles lazily) on wake."""
        self._aot.clear()

    def _call_program(self, program: str, bucket: int, *args):
        """Dispatch one compiled program: the AOT-warmed executable when
        the warmup installed one for this (program, bucket), else the
        lazily-jitted default. An aval/sharding mismatch from the
        executable (e.g. a level-2 wake rebuilt params uncommitted) raises
        TypeError BEFORE execution starts, so the donated cache is
        untouched — drop the stale entry and re-dispatch through jit."""
        comp = self._aot.get((program, bucket))
        if comp is not None:
            try:
                return comp(*args)
            except (TypeError, ValueError):
                # both are pre-execution argument checks (aval mismatch
                # = TypeError, input-sharding mismatch = ValueError), so
                # the donated state is untouched — drop the stale entry
                # and re-dispatch through jit
                self._aot.pop((program, bucket), None)
        if program == "chunk":
            return self.programs.chunk(bucket)(*args)
        if program == "mixed":
            # bucket = mixed_bucket(rows, kvp): the page-table slice
            # width picks the jitted specialization (engine.mixed_bucket)
            return self.programs.mixed(bucket & 0xFFFF)(*args)
        return self._jit_programs[program](*args)

    def _chunk_fn(self, T: int):
        """The T-step decode dispatch target. Gang followers replay this
        name directly (engine/multihost.py) — they never carry AOT
        entries (warmup skips meshes), so they get the bare jit program;
        a single-host engine with an installed chunk executable routes
        through _call_program's AOT-prefer/TypeError-drop dispatch."""
        if ("chunk", T) not in self._aot:
            return self.programs.chunk(T)
        return functools.partial(self._call_program, "chunk", T)

    # -- device scheduler state ---------------------------------------------

    def _h2d_path(self) -> str:
        """step_h2d_bytes attribution for scheduler uploads: the engine's
        serving path (a packed engine's chunk re-uploads are packed-path
        cost; bucketed engines only ever have the bucketed path)."""
        return "packed" if self._packed else "bucketed"

    def _sched_sharding(self):
        """Placement of the device scheduler arrays: plain default-device
        on single-device engines (committed-ness stays out of the jit
        key exactly as before); explicitly REPLICATED on a mesh, so the
        live arrays carry the same sharding the AOT warmup lowers
        against (exec_pool.abstract_args) — an uncommitted array and a
        NamedSharding aval would never match at Compiled-call time.
        Multi-host gang meshes keep the legacy uncommitted placement: a
        host-numpy device_put onto a cross-process sharding is
        jax-version-sensitive, and gangs never carry AOT executables
        (warmup skips followers; the in-program _pin_resident still
        stabilizes their resident state from the second dispatch on)."""
        if self.mesh is None:
            return None
        pidx = jax.process_index()
        if any(d.process_index != pidx for d in self.mesh.devices.flat):
            return None
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh, PartitionSpec())

    #: the [b, vocab] scheduler mirrors the packed path's programs
    #: maintain DEVICE-side between dirty edges — excluded from the
    #: small-tier refresh (_upload_sched_rows)
    _VOCAB_MIRRORS = ("counts", "bias")

    def _sched_mirrors(self) -> Dict[str, np.ndarray]:
        """The ONE canonical name -> host-mirror mapping both upload
        tiers derive from: a mirror added here reaches the full upload
        AND the packed path's small-tier refresh (a hand-maintained
        second dict would silently serve stale device state on packed
        engines only)."""
        return {
            "lt": self._last_tokens,
            "pos": self._positions,
            "budget": self._budgets,
            "pt": self._page_table,
            "temps": self._temps,
            "topp": self._topps,
            "counts": self._token_counts,
            "pres": self._pres,
            "freq": self._freqs,
            "skeys": self._slot_keys,
            "eos_on": self._eos_on,
            "bias": self._bias,
        }

    def _upload_sched(self) -> None:
        """Push host scheduler mirrors to device in ONE batched transfer —
        twelve per-array device_puts are twelve round trips on a
        high-latency link (the axon tunnel), and this runs on every
        post-wake / post-admission chunk (bucketed path) / exact-edge
        packed step. The FULL upload — [b, vocab] counts and bias
        included — is the packed path's dirty-edge fallback; between
        dirty edges packed engines refresh only the small per-slot
        mirrors (_upload_sched_rows)."""
        mirrors = self._sched_mirrors()
        self.step_h2d_bytes[self._h2d_path()] += sum(
            a.nbytes for a in mirrors.values()
        )
        self._dev = jax.device_put(mirrors, self._sched_sharding())
        self._dirty = False
        self._rows_stale = False
        # the pushed [b, vocab] rows are authoritative for every slot;
        # in-program fresh-slot zeroing would discard them
        self._fresh_slots.clear()

    def _upload_sched_rows(self) -> None:
        """Refresh ONLY the small per-slot mirrors on device — everything
        except the [b, vocab] counts/bias, which the packed path's
        programs maintain device-side between dirty edges. O(b ·
        pages_per_seq) bytes vs the full upload's O(b · vocab): this is
        what keeps a packed step's steady-state H2D at O(rows)."""
        small = {
            k: v
            for k, v in self._sched_mirrors().items()
            if k not in self._VOCAB_MIRRORS
        }
        self.step_h2d_bytes[self._h2d_path()] += sum(
            a.nbytes for a in small.values()
        )
        up = jax.device_put(small, self._sched_sharding())
        d = dict(self._dev)
        d.update(up)
        self._dev = d
        self._rows_stale = False

    def _upload_sched_table(self) -> None:
        """Refresh ONLY the device page table — the one piece of device
        state the mixed program reads besides counts/bias (its other
        per-slot inputs arrive as fresh host args each dispatch).
        Leaves _rows_stale SET: the next chunk dispatch still owes the
        full small-tier refresh (it reads lt/pos/budget/... from
        device), but back-to-back packed steps stop re-uploading
        mirrors nobody reads."""
        pt = self._page_table
        self.step_h2d_bytes[self._h2d_path()] += pt.nbytes
        d = dict(self._dev)
        d["pt"] = jax.device_put(pt, self._sched_sharding())
        self._dev = d

    def drop_device_sched_state(self) -> None:
        """Forget device scheduler arrays (sleep path). Host mirrors —
        including the per-slot RNG keys, re-synced after every chunk —
        remain the source of truth; the next chunk re-uploads them.
        Packed engines included: the device-resident counts/bias go with
        the client, and the host mirrors (kept exact — or, for a
        mid-prefill slot, MORE complete than the device copy, which may
        lack a cached prefix's counts while penalties are zero) rebuild
        everything in the next full upload."""
        self._dev = None
        self._dirty = True
        self._rows_stale = False

    def on_device_reacquire(self) -> None:
        """After a device-releasing sleep, the PJRT client was re-created:
        rebuild the engine's device-bound objects (its mesh) on the new
        device handles. Compiled programs re-lower lazily through the
        persistent compile cache; installed AOT executables belonged to
        the destroyed client and are dropped (the service re-validates
        the executable pool on wake)."""
        self.clear_executables()
        if self.mesh is not None:
            from .device import rebuild_mesh

            self.mesh = rebuild_mesh(
                tuple(self.mesh.axis_names), tuple(self.mesh.devices.shape)
            )
            # re-traces pin resident state against the NEW mesh (the old
            # one holds dead device handles)
            self.programs.mesh = self.mesh

    # -- co-resident sibling variants ----------------------------------------

    def variant_hbm_bytes(self) -> int:
        """Device bytes held by attached variant deltas — the accounting
        basis of the service's --variant-hbm-mib admission."""
        return sum(v["nbytes"] for v in self._variants.values())

    def variant_handles(self) -> Dict[int, str]:
        """handle -> label of every attached co-resident variant."""
        return {h: v["label"] for h, v in self._variants.items()}

    def _variant_live(self, handle: int) -> bool:
        if any(r.variant == handle for r in self._waiting):
            return True
        return any(
            r is not None and not r.done and r.variant == handle
            for r in self._slots
        )

    def attach_variant(self, delta: Dict[str, Any], label: str = "") -> int:
        """Make a sibling variant co-resident: device_put its changed
        leaves (flat '/'-keyed host arrays, the chunk_store digest-map
        convention) next to the shared base params and return a stable
        routing handle for add_request. Blocks until the transfer lands
        so the caller's wall clock prices the real H2D. Every delta leaf
        is validated against the base leaf it replaces — a shape/dtype
        mismatch would otherwise surface as a trace error deep inside
        the multi program, unattributable to this attach."""
        if not self._packed:
            raise ValueError(
                "co-resident variants require packed serving: the "
                "bucketed programs always run base params"
            )
        if self.lockstep is not None:
            raise ValueError(
                "co-resident variants are not supported for multi-host "
                "gangs (the lockstep frame has no variant dimension)"
            )
        if self.params is None:
            raise EngineAsleep("engine state is offloaded (sleeping)")
        if not delta:
            raise ValueError(
                "variant delta is empty — an identical sibling needs no "
                "co-residency, route its requests to the base"
            )
        dev: Dict[str, Any] = {}
        nbytes = 0
        for key, leaf in delta.items():
            try:
                base = _leaf_at(self.params, key)
            except (KeyError, IndexError, TypeError):
                raise ValueError(f"variant delta key {key!r} not in params")
            arr = np.asarray(leaf)
            if tuple(arr.shape) != tuple(base.shape) or (
                np.dtype(arr.dtype) != np.dtype(base.dtype)
            ):
                raise ValueError(
                    f"variant delta leaf {key!r} is "
                    f"{arr.dtype}{tuple(arr.shape)}, base is "
                    f"{base.dtype}{tuple(base.shape)}"
                )
            # exact placement of the base leaf it substitutes (sharded
            # on meshes): the multi program's avals must line up
            dev[key] = jax.device_put(arr, base.sharding)
            nbytes += int(arr.nbytes)
        jax.block_until_ready(dev)
        handle = self._next_variant_handle
        self._next_variant_handle += 1
        self._variants[handle] = {
            "delta": dev,
            "nbytes": nbytes,
            "label": label or f"variant-{handle}",
        }
        self._variant_order.append(handle)
        self.variant_attaches += 1
        return handle

    def detach_variant(self, handle: int) -> int:
        """Drop a co-resident variant's device deltas (delta-only
        offload: the host copies live in the tiered pool, nothing moves
        D2H). Refuses while any live request routes to the handle — the
        caller drains or aborts first. Returns the device bytes
        freed."""
        v = self._variants.get(handle)
        if v is None:
            raise KeyError(f"no resident variant with handle {handle}")
        if self._variant_live(handle):
            raise ValueError(
                f"resident variant {handle} has live requests; drain "
                "before detach"
            )
        del self._variants[handle]
        self._variant_order.remove(handle)
        for leaf in v["delta"].values():
            leaf.delete()
        self.variant_detaches += 1
        return int(v["nbytes"])

    def _variant_pass_index(self) -> Dict[int, int]:
        """handle -> pass index v (>= 1) in the multi programs' dense
        dispatch order; base is always pass 0."""
        return {h: i + 1 for i, h in enumerate(self._variant_order)}

    def _variant_deltas(self) -> tuple:
        return tuple(
            self._variants[h]["delta"] for h in self._variant_order
        )

    # -- request lifecycle --------------------------------------------------

    def add_request(
        self,
        prompt: Seq[int],
        max_new_tokens: int = 16,
        temperature: float = 0.0,
        top_p: float = 1.0,
        stop_seqs: Seq[Seq[int]] = (),
        presence_penalty: float = 0.0,
        frequency_penalty: float = 0.0,
        on_token: Optional[Callable[[Request, int], None]] = None,
        want_top_logprobs: bool = False,
        want_prompt_logprobs: bool = False,
        seed: Optional[int] = None,
        ignore_eos: bool = False,
        logit_bias: "Dict[int, float] | None" = None,
        submit_time: Optional[float] = None,
        variant: int = 0,
        trace: Optional[Any] = None,
    ) -> int:
        if not prompt:
            raise ValueError("empty prompt")
        if variant:
            if variant not in self._variants:
                raise ValueError(f"unknown resident variant {variant}")
            if not self._packed:
                raise ValueError(
                    "per-request variant routing requires packed serving"
                )
            if want_prompt_logprobs:
                # echo falls back to the bucketed prompt-logprob prefill
                # programs, which always run base params
                raise ValueError(
                    "echo (prompt logprobs) is not supported for "
                    "variant-routed requests"
                )
        if min(prompt) < 0 or max(prompt) >= self.cfg.model.vocab_size:
            # out-of-range ids would be silently clamped by the embedding
            # gather into garbage output; the HTTP layer pre-clamps, but a
            # request racing a model hot-swap can carry the OLD vocab
            raise ValueError("prompt token id outside vocab")
        if seed is not None and not (-(2**63) <= int(seed) < 2**63):
            # would overflow jax.random.key at admission, inside the
            # engine loop where it can't be attributed to this request
            raise ValueError("seed must fit in a signed 64-bit integer")
        if self.lockstep is not None and logit_bias:
            # like penalties: the [vocab] bias row is too large for the
            # lockstep frame; followers would sample unbiased
            raise ValueError(
                "logit_bias is not supported for multi-host gangs"
            )
        logit_bias = validate_logit_bias(
            logit_bias, self.cfg.model.vocab_size
        )
        if self.lockstep is not None and (presence_penalty or frequency_penalty):
            # penalties need the token-count state, which is too large for
            # the lockstep frame; followers run with zero penalties only
            raise ValueError(
                "repetition penalties are not supported for multi-host gangs"
            )
        total = len(prompt) + max_new_tokens
        if total > self.cfg.seq_len:
            raise ValueError(
                f"prompt+generation {len(prompt)}+{max_new_tokens} exceeds "
                f"max_seq_len {self.cfg.seq_len}"
            )
        if PageAllocator.pages_needed(total, self.cfg.page_size) > self.cfg.num_pages - 1:
            raise ValueError(
                f"request needs {PageAllocator.pages_needed(total, self.cfg.page_size)} "
                f"pages but the pool only has {self.cfg.num_pages - 1}"
            )
        req = Request(
            seq_id=self._next_seq_id,
            prompt=list(prompt),
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            top_p=float(top_p),
            stop_seqs=tuple(tuple(int(t) for t in s) for s in stop_seqs),
            presence_penalty=float(presence_penalty),
            frequency_penalty=float(frequency_penalty),
            on_token=on_token,
            want_top_logprobs=want_top_logprobs,
            want_prompt_logprobs=want_prompt_logprobs,
            seed=seed,
            ignore_eos=ignore_eos,
            logit_bias=logit_bias or {},
            variant=int(variant),
            trace=trace,
        )
        if submit_time is not None:
            # the HTTP layer's enqueue time, not this (possibly later)
            # engine-thread admission: queue-wait and TTFT then cover the
            # whole server-side wait, including the pre-engine pending list
            req.submit_time = submit_time
        self._next_seq_id += 1
        self._waiting.append(req)
        return req.seq_id

    def new_seq_id(self) -> int:
        """Mint a fresh local sequence id. Besides add_request, the
        migration import path uses this to re-key foreign Request
        objects before seating them — two engines' id spaces are
        unrelated and a collision would cross-wire futures."""
        sid = self._next_seq_id
        self._next_seq_id += 1
        return sid

    def _init_slot_key(self, req: Request) -> None:
        if req.rng_key_data is not None:
            # migrated-in seed-None request: the exporter pinned the
            # exact key its own admission would have derived
            self._slot_keys[req.slot] = np.asarray(
                req.rng_key_data, dtype=np.uint32
            )
            return
        if req.seed is not None:
            k = jax.random.key(int(req.seed))
        else:
            k = jax.random.fold_in(
                jax.random.key(self._seed + 1), req.seq_id
            )
        self._slot_keys[req.slot] = np.asarray(jax.random.key_data(k))

    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self._slots):
            if s is None:
                return i
        return None

    def _admit(self, req: Request) -> bool:
        slot = self._free_slot()
        if slot is None:
            return False
        # a blocked request re-attempts every engine step: skip the whole
        # match+alloc dance until allocator or cache state actually moved.
        # Keyed on mutation counters, not sizes: an evict+register of equal
        # sizes changes what is matchable without moving either count.
        state = (
            self.allocator.version,
            self.prefix_cache.version if self.prefix_cache else 0,
        )
        if getattr(req, "_blocked_state", None) == state:
            return False
        total = len(req.prompt) + req.max_new_tokens
        need = PageAllocator.pages_needed(total, self.cfg.page_size)
        shared: List[int] = []
        hashes: List[str] = []
        if (
            self.prefix_cache is not None
            and not req.want_prompt_logprobs
            and req.variant == 0
        ):
            # routed requests never match: the cache indexes pages by
            # prompt tokens only, and a page prefilled under one
            # variant's weights holds that variant's KV — serving it to
            # a sibling would silently cross-contaminate outputs
            shared, req.cached_tokens, hashes = self.prefix_cache.match(
                req.prompt
            )
            # hold the shared pages BEFORE allocating: eviction inside the
            # allocation path must not reclaim what we just matched
            self.prefix_cache.acquire(shared)
        try:
            own = self._alloc_pages(need - len(shared))
        except OutOfPages:
            if self.prefix_cache is not None and shared:
                self.allocator.free(self.prefix_cache.release(shared))
            req.cached_tokens = 0
            req._blocked_state = (
                self.allocator.version,
                self.prefix_cache.version if self.prefix_cache else 0,
            )
            return False
        req.pages = shared + own
        req.shared_pages = len(shared)
        req._prefix_hashes = hashes
        if self.prefix_cache is not None:
            # the sequence's own reference for its non-shared pages (the
            # shared ones were acquired above); hit stats only now that
            # admission actually succeeded
            self.prefix_cache.acquire(own)
            self.prefix_cache.commit(hashes)
        req.slot = slot
        if req.first_sched_time is None:
            # every admission path (bucketed prefill, packed segments,
            # echo fallback) funnels through here: the one stamp that
            # closes the queue-wait window
            req.first_sched_time = time.monotonic()
            if req.trace is not None:
                req.trace.add(
                    "request.queue",
                    req.submit_time,
                    req.first_sched_time,
                    depth=len(self._waiting),
                )
        self._slots[slot] = req
        self._init_slot_key(req)
        self._eos_on[slot] = 0 if req.ignore_eos else 1
        self._bias[slot] = 0.0
        for t, v in req.logit_bias.items():
            self._bias[slot, t] = v
        row = np.zeros((self.cfg.pages_per_seq,), dtype=np.int32)
        row[: len(req.pages)] = req.pages
        self._page_table[slot] = row
        # penalties count prompt tokens too (OpenAI "text so far")
        self._token_counts[slot] = 0
        np.add.at(self._token_counts[slot], req.prompt, 1)
        self._pres[slot] = req.presence_penalty
        self._freqs[slot] = req.frequency_penalty
        # sampling mirrors at admission (the packed program samples from
        # the slot-indexed mirrors mid-prefill; the bucketed prefill
        # re-writes the same values after it runs)
        self._temps[slot] = req.temperature
        self._topps[slot] = req.top_p
        if self._packed:
            # the small mirrors re-upload on the rows edge; counts/bias
            # device rows are handled by the packed step itself (zeroed
            # in-program for fresh slots, full re-upload on exact edges
            # — _step_packed decides which). The echo fallback's
            # _run_prefill still forces the full dirty edge.
            self._rows_stale = True
        else:
            self._dirty = True
        return True

    def _alloc_pages(self, n: int) -> List[int]:
        """Allocate, evicting LRU cache-only prefix pages under pressure."""
        try:
            return self.allocator.alloc(n)
        except OutOfPages:
            if self.prefix_cache is None:
                raise
            evicted = self.prefix_cache.evict(n - self.allocator.available)
            if not evicted:
                raise
            self.allocator.free(evicted)
            return self.allocator.alloc(n)

    def _prefill_bucket(self, n: int) -> int:
        return prefill_bucket(n, self.cfg.seq_len)

    def _run_suffix_segment(
        self, req: Request, start_pos: int, seg: List[int], temp, topp,
        counts_row, pres, freq, final: bool,
    ):
        """One prefill segment via the continue program: scatter the
        segment's KV, attend over everything already in the pages. Used by
        prefix-cache hits AND chunked prefill (a segment at start 0 works
        too: its own KV is scattered before the paged attention).

        Only the FINAL segment advances the RNG key: non-final segments'
        in-program sample is discarded, so a chunked prefill consumes
        exactly one key split — the same as an unchunked one — and
        temperature>0 outputs are identical either way."""
        table = self._page_table[req.slot : req.slot + 1]
        bucket = self._prefill_bucket(len(seg))
        self.pad_waste_bytes["bucketed"] += (
            (bucket - len(seg)) * self._pad_token_bytes
        )
        self.dispatch_tokens["bucketed"] += len(seg)
        tokens = np.zeros((1, bucket), dtype=np.int32)
        tokens[0, : len(seg)] = seg
        # next prompt token at each segment position (prompt-logprob
        # targets); the final position of the final segment has none
        targets = np.zeros((1, bucket), dtype=np.int32)
        nxt = req.prompt[start_pos + 1 : start_pos + len(seg) + 1]
        targets[0, : len(nxt)] = nxt
        start = np.array([start_pos], dtype=np.int32)
        seg_lens = np.array([len(seg)], dtype=np.int32)
        if self.lockstep is not None:
            self.lockstep.prefill_suffix(
                req, bucket, start_pos, len(seg), advance_key=final,
                want_plp=req.want_prompt_logprobs,
            )
        self.step_h2d_bytes["bucketed"] += (
            tokens.nbytes + targets.nbytes + start.nbytes + seg_lens.nbytes
            + table.nbytes + temp.nbytes + topp.nbytes + counts_row.nbytes
            + pres.nbytes + freq.nbytes + self._slot_keys[req.slot].nbytes
            + self._bias[req.slot : req.slot + 1].nbytes
        )
        tok, lp, av, ai, plp, cache, new_key = self._call_program(
            "suffix_plp" if req.want_prompt_logprobs else "suffix",
            bucket,
            self.params,
            tokens,
            targets,
            start,
            seg_lens,
            self.pool.as_tuple(),
            table,
            temp,
            topp,
            counts_row,
            pres,
            freq,
            self._slot_keys[req.slot],
            self._bias[req.slot : req.slot + 1],
        )
        self.pool.replace(cache)
        # key sync is the caller's: it batches it with the other host reads
        return tok, lp, av, ai, plp, new_key

    def _run_prefill(self, req: Request) -> None:
        n = len(req.prompt)
        temp = np.asarray([req.temperature], dtype=np.float32)
        topp = np.asarray([req.top_p], dtype=np.float32)
        counts_row = self._token_counts[req.slot : req.slot + 1]
        pres = np.asarray([req.presence_penalty], dtype=np.float32)
        freq = np.asarray([req.frequency_penalty], dtype=np.float32)
        k = req.cached_tokens
        limit = self.cfg.max_prefill_tokens or (n - k)
        if k == 0 and n <= limit:
            # single cold segment: the flash-style causal program
            table = self._page_table[req.slot : req.slot + 1]
            bucket = self._prefill_bucket(n)
            self.pad_waste_bytes["bucketed"] += (
                (bucket - n) * self._pad_token_bytes
            )
            self.dispatch_tokens["bucketed"] += n
            tokens = np.zeros((1, bucket), dtype=np.int32)
            tokens[0, :n] = req.prompt
            seq_lens = np.array([n], dtype=np.int32)
            if self.lockstep is not None:
                self.lockstep.prefill(
                    req, bucket, want_plp=req.want_prompt_logprobs
                )
            self.step_h2d_bytes["bucketed"] += (
                tokens.nbytes + seq_lens.nbytes + table.nbytes + temp.nbytes
                + topp.nbytes + counts_row.nbytes + pres.nbytes + freq.nbytes
                + self._slot_keys[req.slot].nbytes
                + self._bias[req.slot : req.slot + 1].nbytes
            )
            tok, lp, av, ai, plp, cache, new_key = self._call_program(
                "prefill_plp" if req.want_prompt_logprobs else "prefill",
                bucket,
                self.params,
                tokens,
                seq_lens,
                self.pool.as_tuple(),
                table,
                temp,
                topp,
                counts_row,
                pres,
                freq,
                self._slot_keys[req.slot],
                self._bias[req.slot : req.slot + 1],
            )
            self.pool.replace(cache)
            if req.want_prompt_logprobs:
                # device refs only; fetched in the single batched sync below
                plp_parts = [(plp, n - 1)]
        else:
            # prefix-cache hit and/or chunked prefill: run [k, n) through
            # the continue program in segments of <= limit tokens; only the
            # final segment's sample is consumed
            pos = k
            plp_parts = []
            while pos < n:
                seg = req.prompt[pos : min(n, pos + limit)]
                final = pos + len(seg) >= n
                tok, lp, av, ai, plp, seg_key = self._run_suffix_segment(
                    req, pos, seg, temp, topp, counts_row, pres, freq,
                    final=final,
                )
                if final:
                    new_key = seg_key
                if req.want_prompt_logprobs:
                    # entries predict prompt[pos+1 .. pos+len(seg)]; the
                    # final segment's last entry predicts nothing
                    take = len(seg) if not final else len(seg) - 1
                    plp_parts.append((plp, take))
                pos += len(seg)
        if self.prefix_cache is not None and req.variant == 0:
            # the full prompt pages now hold prompt KV: make them
            # reusable (base-variant KV only — see _admit's match gate)
            self.prefix_cache.register(
                req.prompt,
                req.pages,
                req.shared_pages,
                known_hashes=getattr(req, "_prefix_hashes", ()),
            )
        # ONE batched host sync for everything the emit needs — separate
        # np.asarray calls are separate round trips on high-latency links,
        # and this is the tail of every TTFT measurement. Prompt-logprob
        # rows (one per prefill segment) ride the same fetch.
        fetch = [tok, lp, new_key]
        if req.want_top_logprobs:
            fetch += [av, ai]
        if req.want_prompt_logprobs:
            fetch += [p for p, _ in plp_parts]
        vals = list(jax.device_get(tuple(fetch)))
        tok_h, lp_h, key_h = vals[:3]
        vals = vals[3:]
        alts = None
        if req.want_top_logprobs:
            av_h, ai_h = vals[:2]
            vals = vals[2:]
            alts = [
                (int(ai_h[0, j]), float(av_h[0, j]))
                for j in range(av_h.shape[1])
            ]
        if req.want_prompt_logprobs:
            req.prompt_logprobs = [None]  # nothing precedes token 0
            for row, (_, take) in zip(vals, plp_parts):
                req.prompt_logprobs.extend(
                    float(row[0][i]) for i in range(take)
                )
        self._slot_keys[req.slot] = key_h
        first = int(tok_h[0])
        req.pos = n
        self._emit(req, first, float(lp_h[0]), alts)
        self._positions[req.slot] = req.pos  # position of the token to place
        self._last_tokens[req.slot] = first
        self._temps[req.slot] = req.temperature
        self._topps[req.slot] = req.top_p
        self._budgets[req.slot] = req.max_new_tokens - len(req.out_tokens)
        self._dirty = True

    def _emit(
        self,
        req: Request,
        token: int,
        logprob: float = 0.0,
        alts: Optional[list] = None,
    ) -> None:
        if req.first_token_time is None:
            req.first_token_time = time.monotonic()
            if (
                req.trace is not None
                and req.first_sched_time is not None
                and not req.out_tokens
            ):
                # out_tokens non-empty with no first_token_time = a
                # migrated-in mid-decode request: its prefill happened
                # on the source; don't mislabel the re-seat window
                req.trace.add(
                    "request.prefill",
                    req.first_sched_time,
                    req.first_token_time,
                    prompt_tokens=len(req.prompt),
                    cached_tokens=req.cached_tokens,
                    packed=bool(self._packed),
                )
        req.out_tokens.append(token)
        req.out_logprobs.append(logprob)
        req.out_top_logprobs.append(alts or [])
        self.total_tokens_emitted += 1
        if req.slot >= 0:
            # host counts mirror the device copy the chunk program updates
            # (stop-stripped tokens stay counted on both sides)
            self._token_counts[req.slot, token] += 1
        for seq in req.stop_seqs:
            if len(req.out_tokens) >= len(seq) and tuple(
                req.out_tokens[-len(seq):]
            ) == seq:
                # OpenAI semantics: finish on the stop sequence and strip it
                del req.out_tokens[-len(seq):]
                del req.out_logprobs[-len(seq):]
                del req.out_top_logprobs[-len(seq):]
                req.done = True
                req.finish_reason = "stop"
                break
        if not req.done:
            eos_hit = (
                token == self.cfg.eos_token_id
                or token in self.cfg.extra_eos_ids
            ) and not req.ignore_eos
            if req.stop_requested or eos_hit:
                req.done = True
                req.finish_reason = "stop"
            elif len(req.out_tokens) >= req.max_new_tokens:
                req.done = True
                req.finish_reason = "length"
        self._stream(req)

    def _stream(self, req: Request) -> None:
        """Deliver newly-safe tokens to the streaming hook.

        Tokens forming a suffix of the output that is a proper prefix of a
        stop sequence are held back — they may yet be stripped. On finish,
        everything that survived stripping is flushed; consumers see
        `req.done` only on the final delivered token (the SSE writer keys
        its terminator on it)."""
        if req.on_token is None:
            return
        if req.done:
            tail = req.out_tokens[req.streamed:]
        else:
            hold = _stop_holdback(req.out_tokens, req.stop_seqs)
            tail = req.out_tokens[req.streamed : len(req.out_tokens) - hold]
        if not tail:
            return
        # advance the cursor per delivered token: an on_token exception
        # mid-flush must leave the rest re-flushable on the next emit
        was_done = req.done
        try:
            for i, t in enumerate(tail):
                req.done = was_done and i == len(tail) - 1
                req.on_token(req, t)
                req.streamed += 1
        finally:
            req.done = was_done

    def _retire(self, req: Request) -> None:
        if self.prefix_cache is not None:
            self.allocator.free(self.prefix_cache.release(req.pages))
        else:
            self.allocator.free(req.pages)
        self._slots[req.slot] = None
        self._page_table[req.slot] = 0
        self._positions[req.slot] = 0
        self._last_tokens[req.slot] = 0
        self._temps[req.slot] = 0.0
        self._topps[req.slot] = 1.0
        self._pres[req.slot] = 0.0
        self._freqs[req.slot] = 0.0
        self._token_counts[req.slot] = 0
        self._budgets[req.slot] = 0
        self._slot_keys[req.slot] = 0
        self._eos_on[req.slot] = 1
        self._bias[req.slot] = 0.0
        req.slot = -1
        if self._packed:
            # a retired slot's device counts/bias rows go stale-but-
            # frozen: the chunk program never samples a zero-budget slot
            # into anything the host reads, and the next packed
            # admission into the slot zeroes the rows in-program
            # (fresh_on) — no O(b·vocab) re-upload per retire edge
            self._rows_stale = True
        else:
            self._dirty = True

    # -- token-packed mixed-batch serving (cfg.packed_serving) ---------------

    def _any_prefilling(self) -> bool:
        return any(
            r is not None and r.prefilling and not r.done
            for r in self._slots
        )

    def _packed_shapes(self) -> List[int]:
        return packed_budget_shapes(self.cfg)

    def _step_packed(self, finished: List[Request]) -> bool:
        """One token-packed mixed-batch step: pack a decode row per
        running sequence plus prefill segments from the in-flight and
        waiting queues into the flat [token_budget] buffer, dispatch the
        ONE mixed program, and emit at most one token per sequence.

        Returns False without dispatching when no prefill segment could
        be packed (the waiting queue is blocked on slots/pages) — the
        caller then runs the fused decode chunk instead, so a blocked
        queue never degrades decode to one token per dispatch.

        Packing layout (the ragged kernel's contract, ops/pallas/
        ragged.py): each sequence's rows are contiguous with consecutive
        positions and start on a RAGGED_BLOCK boundary; alignment gaps
        and the buffer tail are padding rows (row_slot = -1) the model
        computes but nobody reads.
        """
        from ..utils import tracing

        qb = self._pack_align
        T = self._token_budget
        b = self.cfg.max_batch
        tokens = np.zeros((T,), dtype=np.int32)
        row_slot = np.full((T,), -1, dtype=np.int32)
        positions = np.zeros((T,), dtype=np.int32)
        #: rows whose token joins its slot's device count row BEFORE the
        #: sampling tail: streamed prompt tokens (decode rows' tokens
        #: were already counted when they were emitted)
        count_row = np.zeros((T,), dtype=np.int32)
        sample_rows = np.zeros((b,), dtype=np.int32)
        sample_on = np.zeros((b,), dtype=np.int32)
        #: per-row variant pass index (co-resident routing): all-zero
        #: buffers dispatch the plain mixed program — attach_variant
        #: with no routed traffic is off-inert, AOT warmup included
        tok_variant = np.zeros((T,), dtype=np.int32)
        vmap_idx = self._variant_pass_index() if self._variants else {}
        rows_used = 0
        decode_reqs: List[Request] = []
        segments: List[Tuple[Request, int, bool]] = []
        seg_cap = self.cfg.max_prefill_tokens or T

        def pack_segment(req: Request) -> bool:
            nonlocal rows_used
            room = T - rows_used
            if room < qb:
                return False
            take = min(len(req.prompt) - req.pos, seg_cap, room)
            if take <= 0:
                return False
            start = rows_used
            tokens[start : start + take] = req.prompt[
                req.pos : req.pos + take
            ]
            row_slot[start : start + take] = req.slot
            if req.variant:
                tok_variant[start : start + take] = vmap_idx[req.variant]
            positions[start : start + take] = np.arange(
                req.pos, req.pos + take, dtype=np.int32
            )
            count_row[start : start + take] = 1
            final = req.pos + take >= len(req.prompt)
            if final:
                # the segment's last row predicts the first generated token
                sample_rows[req.slot] = start + take - 1
                sample_on[req.slot] = 1
            segments.append((req, take, final))
            rows_used += -(-take // qb) * qb
            return True

        # 1. one decode row per running sequence — decode NEVER stalls
        #    behind prefill; each row owns an aligned block (a kernel
        #    block holds exactly one sequence)
        for slot, req in enumerate(self._slots):
            if req is None or req.done or req.prefilling:
                continue
            tokens[rows_used] = self._last_tokens[slot]
            row_slot[rows_used] = slot
            positions[rows_used] = req.pos
            if req.variant:
                tok_variant[rows_used] = vmap_idx[req.variant]
            sample_rows[slot] = rows_used
            sample_on[slot] = 1
            decode_reqs.append(req)
            rows_used += qb

        # 2. advance in-flight chunked prefills (slot order), one segment
        #    each per step (max_prefill_tokens bounds segment length)
        for req in self._slots:
            if req is not None and req.prefilling and not req.done:
                pack_segment(req)

        # 3. admit waiting requests into the remaining budget
        while self._waiting and T - rows_used >= qb:
            req = self._waiting[0]
            if req.want_prompt_logprobs:
                # echo requests need the full-bucket prompt-logprob
                # scoring variants: bucketed fallback, same step
                if not self._admit(req):
                    break
                self._waiting.pop(0)
                self._run_prefill(req)
                if req.done:
                    self._retire(req)
                    finished.append(req)
                continue
            if not self._admit(req):
                break
            self._waiting.pop(0)
            req.prefilling = True
            req.pos = req.cached_tokens
            # Device-resident counts: the host mirror follows the
            # STREAMING semantics the mixed program implements — cached-
            # prefix counts now (those tokens never enter the buffer),
            # packed rows as they stream (below). _admit's full-prompt
            # count is rewritten; the echo fallback above keeps it.
            self._token_counts[req.slot] = 0
            if req.cached_tokens:
                np.add.at(
                    self._token_counts[req.slot],
                    req.prompt[: req.cached_tokens], 1,
                )
            if req.logit_bias or (
                (req.presence_penalty or req.frequency_penalty)
                and req.cached_tokens
            ):
                # exact edges the program can't reproduce from the
                # buffer: a non-zero bias row, or penalties over a
                # cached prefix whose tokens never stream — fall back to
                # the full mirror re-upload for this step
                self._dirty = True
            else:
                self._fresh_slots.add(req.slot)
            pack_segment(req)

        if not segments:
            # nothing but decode rows: the fused chunk path serves the
            # running batch better (decode_chunk tokens per dispatch)
            return False

        # dispatch at the smallest compiled buffer shape that fits (one
        # or two shapes ever compile; _packed_shapes), against the
        # device-resident page table sliced IN-PROGRAM to the power-of-
        # two-ish width the step's longest sequence needs — bit-exact,
        # and it bounds the reference twin's gather by live context
        # instead of max_seq (mixed_bucket)
        shape = next(s for s in self._packed_shapes() if s >= rows_used)
        vmask = row_slot[:shape] >= 0
        valid = int(vmask.sum())
        max_kv = int(positions[:shape][vmask].max()) + 1
        kvp = kv_pages_bucket(
            max_kv, self.cfg.page_size, self.cfg.pages_per_seq
        )
        prefill_tokens = sum(t for _, t, _ in segments)
        self.packed_steps += 1
        self.packed_tokens_total += valid
        self.pad_waste_bytes["packed"] += (
            (shape - valid) * self._pad_token_bytes
        )
        self.dispatch_tokens["packed"] += valid
        # Scheduler state sync, cheapest sufficient tier: a dirty edge
        # (exact-count/bias admission, echo fallback, sleep/wake drop)
        # pushes the full mirrors — and makes the in-program fresh-slot
        # zeroing moot; otherwise only the small per-slot mirrors
        # refresh (the mixed program needs the page table rows the
        # admissions just wrote). Ordering matters: the upload must
        # precede the host-side streamed-count adds below, because the
        # program pre-adds the same rows on device either way.
        fresh_on = np.zeros((b,), dtype=np.int32)
        if self._dirty or self._dev is None:
            self._upload_sched()
        else:
            if self._fresh_slots:
                fresh_on[list(self._fresh_slots)] = 1
            if self._rows_stale:
                self._upload_sched_table()
        d = self._dev
        # any routed row switches the step to the multi-variant twin —
        # an all-base buffer keeps the plain (possibly AOT-warmed)
        # program, so co-residency costs base traffic nothing
        routed_rows = int((tok_variant[:shape] > 0).sum())
        self.step_h2d_bytes["packed"] += (
            tokens[:shape].nbytes + row_slot[:shape].nbytes
            + positions[:shape].nbytes + count_row[:shape].nbytes
            + sample_rows.nbytes + sample_on.nbytes + fresh_on.nbytes
            + self._temps.nbytes + self._topps.nbytes + self._pres.nbytes
            + self._freqs.nbytes + self._slot_keys.nbytes
            + (tok_variant[:shape].nbytes if routed_rows else 0)
        )
        self.last_step_stats = {
            "mode": "packed",
            "rows": shape,
            "tokens": valid,
            "pad_rows": shape - valid,
            "decode_rows": len(decode_reqs),
            "prefill_tokens": prefill_tokens,
            "routed_rows": routed_rows,
        }
        with tracing.span(
            "step.packed", rows=shape, tokens=valid,
            decode_rows=len(decode_reqs), prefill_tokens=prefill_tokens,
        ):
            if routed_rows:
                tok, lp, av, ai, cache, counts_dev, bias_dev, skeys = (
                    self.programs.mixed_multi(kvp)(
                        self.params,
                        self._variant_deltas(),
                        tok_variant[:shape],
                        tokens[:shape],
                        row_slot[:shape],
                        positions[:shape],
                        count_row[:shape],
                        sample_rows,
                        sample_on,
                        fresh_on,
                        self.pool.as_tuple(),
                        d["pt"],
                        self._temps,
                        self._topps,
                        d["counts"],
                        self._pres,
                        self._freqs,
                        self._slot_keys,
                        d["bias"],
                    )
                )
            else:
                tok, lp, av, ai, cache, counts_dev, bias_dev, skeys = (
                    self._call_program(
                        "mixed", mixed_bucket(shape, kvp),
                        self.params,
                        tokens[:shape],
                        row_slot[:shape],
                        positions[:shape],
                        count_row[:shape],
                        sample_rows,
                        sample_on,
                        fresh_on,
                        self.pool.as_tuple(),
                        d["pt"],
                        self._temps,
                        self._topps,
                        d["counts"],
                        self._pres,
                        self._freqs,
                        self._slot_keys,
                        d["bias"],
                    )
                )
            self.pool.replace(cache)
            # the program consumed (donated) and re-emitted the device-
            # resident mirrors; they stay the between-dispatch truth
            d["counts"] = counts_dev
            d["bias"] = bias_dev
            self._fresh_slots.clear()
            # ONE batched host sync for the whole step's emits
            tok_h, lp_h, av_h, ai_h, keys_h = jax.device_get(
                (tok, lp, av, ai, skeys)
            )
        # non-sampling slots' keys came back unchanged (in-program where)
        self._slot_keys[:] = keys_h
        # host count mirrors absorb the streamed prompt rows exactly as
        # the program pre-added them on device (req.pos still pre-step)
        for req, take, _final in segments:
            if req.slot >= 0:
                np.add.at(
                    self._token_counts[req.slot],
                    req.prompt[req.pos : req.pos + take], 1,
                )

        def alts_for(req: Request, slot: int):
            if not req.want_top_logprobs:
                return None
            return [
                (int(ai_h[slot, j]), float(av_h[slot, j]))
                for j in range(av_h.shape[1])
            ]

        # prefill segments advance; final segments emit their first token
        for req, take, final in segments:
            if req.done:  # aborted mid-step: pages already freed
                continue
            slot = req.slot
            req.pos += take
            if not final:
                continue
            req.prefilling = False
            if self.prefix_cache is not None and req.variant == 0:
                # the full prompt's KV is now in pages: make it reusable
                # (base-variant KV only — see _admit's match gate)
                self.prefix_cache.register(
                    req.prompt, req.pages, req.shared_pages,
                    known_hashes=getattr(req, "_prefix_hashes", ()),
                )
            first = int(tok_h[slot])
            self._emit(req, first, float(lp_h[slot]), alts_for(req, slot))
            self._positions[slot] = req.pos
            self._last_tokens[slot] = first
            self._budgets[slot] = req.max_new_tokens - len(req.out_tokens)
            if req.done:
                self._retire(req)
                finished.append(req)
        # decode rows emit one token each
        for req in decode_reqs:
            if req.done:
                continue
            slot = req.slot
            t = int(tok_h[slot])
            req.pos += 1
            self._positions[slot] = req.pos
            self._last_tokens[slot] = t
            self._emit(req, t, float(lp_h[slot]), alts_for(req, slot))
            self._budgets[slot] = req.max_new_tokens - len(req.out_tokens)
            if req.done:
                self._retire(req)
                finished.append(req)
        # the [b, vocab] device mirrors are already exact (the program
        # maintained them); only the small per-slot mirrors (last
        # tokens, positions, budgets — advanced by the emits above)
        # need the next dispatch to refresh them
        self._rows_stale = True
        return True

    # -- speculative (n-gram / prompt-lookup) decoding -----------------------

    def _spec_candidate(self) -> Optional[Request]:
        """Speculation engages only where it is exact and simple: exactly
        one greedy (temp=0, full top-p) sequence in flight, nothing
        waiting, no gang lockstep."""
        if self.cfg.speculative_ngram <= 0 or self.lockstep is not None:
            return None
        if self._waiting:
            return None
        # a mid-prefill slot (packed serving) has no sampled token yet —
        # its last-token mirror is not a valid speculation context
        active = [
            r
            for r in self._slots
            if r is not None and not r.done and not r.prefilling
        ]
        if len(active) != 1:
            return None
        r = active[0]
        # only transforms that shift the argmax gate exactness: at
        # temperature 0 sampling is the full-vocab argmax regardless of
        # top_p, and streaming (on_token) already receives multi-token
        # bursts from the chunk path — but repetition penalties DO move
        # the argmax, and the verify program doesn't apply them
        if (
            r.temperature != 0.0
            or r.presence_penalty != 0.0
            or r.frequency_penalty != 0.0
            or r.logit_bias
        ):
            return None
        if r.variant != 0:
            # the verify program runs base params; accepting a routed
            # request's proposals would verify against the wrong weights
            return None
        return r

    def _propose_ngram(self, req: Request, k: int) -> List[int]:
        """Prompt-lookup proposal: find the most recent PREVIOUS occurrence
        of the context's trailing m-gram (m = 3, 2) and propose the tokens
        that followed it."""
        # bounded lookback: an unbounded backward scan (or a full-context
        # concat) is O(context) host work per decode step — build only the
        # trailing window (vLLM caps its ngram lookup the same way)
        lookback = 1024 + k
        out = req.out_tokens
        if len(out) >= lookback:
            ctx = out[-lookback:]
        else:
            ctx = req.prompt[-(lookback - len(out)):] + out
        for m in (3, 2):
            if len(ctx) <= m:
                continue
            tail = ctx[-m:]
            for i in range(len(ctx) - m - 1, -1, -1):
                if ctx[i : i + m] == tail:
                    props = ctx[i + m : i + m + k]
                    if props:
                        return props
        return []

    def _spec_round(self, req: Request) -> bool:
        """One speculative verify round. Returns True if it ran (the caller
        skips the normal chunk step), False to fall back.

        Window [t0, q1..qk] runs through the verify program (the continue
        program + argmax): o[i] is the model's greedy token after
        window[:i+1]. Accept q_{i+1} while o[i] == q_{i+1}; the first
        mismatch's o is the corrected token, and a fully-accepted window
        yields o[k] as a bonus token — up to k+1 tokens per forward.
        Rejected tokens' KV stays in pages beyond `positions` where the
        attention mask never looks; it is overwritten as decoding reaches
        those positions."""
        k = min(
            self.cfg.speculative_ngram,
            req.max_new_tokens - len(req.out_tokens),
            self.cfg.seq_len - req.pos - 1,
        )
        if k <= 0:
            return False
        if self._spec_cooldown > 0:
            # acceptance-rate hysteresis: after a run of fully-rejected
            # rounds, speculation costs a verify forward per single token
            # (vs the fused chunk); back off to the chunk path for a while
            self._spec_cooldown -= 1
            return False
        props = self._propose_ngram(req, k)
        if not props:
            return False
        window = [int(self._last_tokens[req.slot])] + props
        bucket = self._prefill_bucket(len(window))
        tokens = np.zeros((1, bucket), dtype=np.int32)
        tokens[0, : len(window)] = window
        start = np.array([req.pos], dtype=np.int32)
        window_len = np.array([len(window)], dtype=np.int32)
        table = self._page_table[req.slot : req.slot + 1]
        self.step_h2d_bytes["bucketed"] += (
            tokens.nbytes + start.nbytes + window_len.nbytes + table.nbytes
        )
        toks, lps_dev, avs_dev, ais_dev, cache = self._verify_fn(
            self.params, tokens, start, window_len, self.pool.as_tuple(), table
        )
        self.pool.replace(cache)
        # one batched host sync (4 separate np.asarray = 4 round trips)
        o, o_lp, o_av, o_ai = (
            x[0] for x in jax.device_get((toks, lps_dev, avs_dev, ais_dev))
        )
        self.spec_proposed += len(props)
        accepted = 0
        emitted: List[Tuple[int, float, list]] = []

        def _spec_alts(i):
            if not req.want_top_logprobs:
                return None
            return [
                (int(o_ai[i, j]), float(o_av[i, j]))
                for j in range(o_av.shape[1])
            ]

        for i, q in enumerate(props):
            if int(o[i]) != q:
                # corrected token
                emitted.append((int(o[i]), float(o_lp[i]), _spec_alts(i)))
                break
            accepted += 1
            emitted.append((q, float(o_lp[i]), _spec_alts(i)))
        else:
            i = len(props)
            emitted.append((int(o[i]), float(o_lp[i]), _spec_alts(i)))
        self.spec_accepted += accepted
        if accepted == 0:
            self._spec_miss_streak += 1
            if self._spec_miss_streak >= 4:
                self._spec_cooldown = 32
                self._spec_miss_streak = 0
        else:
            self._spec_miss_streak = 0
        for t, lp, alts in emitted:
            req.pos += 1
            self._positions[req.slot] = req.pos
            self._last_tokens[req.slot] = t
            self._budgets[req.slot] = max(
                0, req.max_new_tokens - len(req.out_tokens) - 1
            )
            self._emit(req, t, lp, alts)
            if req.done:
                break
        self._dirty = True  # device scheduler state is stale
        return True

    # -- the engine loop body ----------------------------------------------

    def step(self) -> List[Request]:
        """Admit + prefill waiting requests, then one decode *chunk* (up to
        ``decode_chunk`` fused steps) for the running batch. Returns requests
        that finished."""
        if self.params is None:
            raise EngineAsleep("engine state is offloaded (sleeping)")
        self.last_step_stats = None
        finished: List[Request] = list(self._orphan_finished)
        self._orphan_finished.clear()

        # Token-packed mixed-batch path (cfg.packed_serving): whenever
        # packable prefill work is pending, ONE mixed program carries
        # prefill segments AND a decode row per running sequence, then
        # the step FALLS THROUGH to the fused decode chunk below — the
        # same prefill-then-chunk step shape as the bucketed path, so
        # decode keeps its decode_chunk-per-dispatch fusion while
        # prompts neither serialize behind each other nor stall it (the
        # mixed step's decode rows are the no-stall bonus token). A
        # waiting queue blocked on slots/pages packs nothing and goes
        # straight to the chunk.
        packed_mode = self._packed and self.lockstep is None
        if packed_mode and (self._waiting or self._any_prefilling()):
            self._step_packed(finished)

        if not packed_mode:
            while self._waiting:
                req = self._waiting[0]
                if not self._admit(req):
                    break
                self._waiting.pop(0)
                self._run_prefill(req)
                if req.done:
                    self._retire(req)
                    finished.append(req)

        # speculation never interleaves with an in-flight chunk: a verify
        # forward would race the chunk's decode of the same slot
        spec_req = self._spec_candidate() if self._inflight is None else None
        if spec_req is not None and self._spec_round(spec_req):
            if spec_req.done:
                self._retire(spec_req)
                finished.append(spec_req)
            return finished

        pipelined = self.cfg.pipeline_decode and self.lockstep is None
        if not pipelined:
            running = self._running()
            if running:
                finished.extend(
                    self._drain_chunk(self._dispatch_chunk(running))
                )
            return finished

        # Pipelined (double-buffered) decode: dispatch chunk k+1 BEFORE
        # reading chunk k's results, so the device computes k+1 while the
        # host fetches and emits k — hiding the dispatch/fetch round trip
        # that dominates decode on high-latency links (docs/perf.md).
        # Page-safety invariant: a chunk dispatched after a request's
        # finish became known never writes its slot (host finishes freeze
        # the budget mirror and mark it dirty, and a dirty state forces
        # drain-then-reupload ordering below), so a finished request's
        # pages may be written only by the ONE chunk already in flight —
        # its retire (page free / prefix-cache registration) is deferred
        # until that chunk drains (_defer_retire).
        if self._inflight is not None:
            running = self._running()
            nxt = None
            if running and not self._dirty and not self._waiting:
                # End-of-batch tail: when every running request's remaining
                # budget fits inside the chunk already in flight, that chunk
                # finishes them all (budget exhaustion is unconditional, eos
                # can only finish earlier) and a speculative chunk k+1 would
                # be fully frozen — skip it and drain-then-dispatch at this
                # boundary instead of burning a wasted chunk of device work
                # plus one chunk of tail latency.
                t_inflight = self._inflight[6]
                if any(
                    r.max_new_tokens - len(r.out_tokens) > t_inflight
                    for r in running.values()
                ):
                    nxt = self._dispatch_chunk(running)
            inflight, self._inflight = self._inflight, None
            ready, self._pending_retire = self._pending_retire, []
            finished.extend(self._drain_chunk(inflight, defer_retire=True))
            for r in ready:
                # the chunk that could still write these slots has drained
                self._retire(r)
            self._inflight = nxt
            if nxt is None:
                for r in self._pending_retire:
                    self._retire(r)
                self._pending_retire = []
            return finished
        running = self._running()
        if running:
            self._inflight = self._dispatch_chunk(running)
        return finished

    def _running(self) -> Dict[int, Request]:
        # mid-prefill slots (packed serving) are not decodable yet: their
        # budget mirror is 0, and the packed branch guarantees the chunk
        # program never dispatches while any slot is prefilling
        return {
            r.slot: r
            for r in self._slots
            if r is not None and not r.done and not r.prefilling
        }

    def _dispatch_chunk(self, running: Dict[int, Request]):
        """Dispatch one compiled decode chunk (async — jax returns
        futures); the matching _drain_chunk does the single host sync."""
        max_remaining = max(
            r.max_new_tokens - len(r.out_tokens) for r in running.values()
        )
        # At most two compiled chunk programs (T=decode_chunk and T=1):
        # compiles are expensive on TPU, and a serving engine at steady
        # state always has >= decode_chunk tokens of demand. The drain
        # tail of a batch run follows cfg.drain_tail (single steps, or
        # one full chunk with the surplus steps frozen in-program).
        if max_remaining >= self.cfg.decode_chunk or self._drain_tail_chunk:
            T = self.cfg.decode_chunk
        else:
            T = 1
        reupload = self._dirty or self._dev is None
        if self.lockstep is not None:
            self.lockstep.chunk(T, reupload)
        if reupload:
            self._upload_sched()
        elif self._rows_stale:
            # packed engines only: the mixed step advanced the small
            # per-slot mirrors host-side (and admissions/retires touched
            # the page table); the [b, vocab] counts stay device-exact
            self._upload_sched_rows()
        d = self._dev
        # a routed slot switches the chunk to the multi-variant twin
        # (the plain program would decode it with base weights); with
        # none live the plain, possibly AOT-warmed chunk serves as ever
        if any(r.variant != 0 for r in running.values()):
            vmap_idx = self._variant_pass_index()
            slot_variant = np.zeros((self.cfg.max_batch,), dtype=np.int32)
            for slot, r in running.items():
                if r.variant:
                    slot_variant[slot] = vmap_idx[r.variant]
            self.step_h2d_bytes[self._h2d_path()] += slot_variant.nbytes
            (
                toks_dev, lps_dev, avs_dev, ais_dev, lt, pos, budget,
                cache, counts_dev, skeys_dev,
            ) = self.programs.chunk_multi(T)(
                self.params,
                self._variant_deltas(),
                slot_variant,
                d["lt"],
                d["pos"],
                d["budget"],
                self.pool.as_tuple(),
                d["pt"],
                d["temps"],
                d["topp"],
                d["counts"],
                d["pres"],
                d["freq"],
                d["skeys"],
                d["eos_on"],
                d["bias"],
            )
        else:
            (
                toks_dev, lps_dev, avs_dev, ais_dev, lt, pos, budget,
                cache, counts_dev, skeys_dev,
            ) = self._chunk_fn(T)(
                self.params,
                d["lt"],
                d["pos"],
                d["budget"],
                self.pool.as_tuple(),
                d["pt"],
                d["temps"],
                d["topp"],
                d["counts"],
                d["pres"],
                d["freq"],
                d["skeys"],
                d["eos_on"],
                d["bias"],
            )
        self.pool.replace(cache)
        self._dev = {
            "lt": lt, "pos": pos, "budget": budget,
            "pt": d["pt"], "temps": d["temps"], "topp": d["topp"],
            "counts": counts_dev, "pres": d["pres"], "freq": d["freq"],
            "skeys": skeys_dev, "eos_on": d["eos_on"], "bias": d["bias"],
        }
        return (toks_dev, lps_dev, avs_dev, ais_dev, skeys_dev, running, T)

    def _drain_chunk(self, inflight, defer_retire: bool = False):
        """Fetch one dispatched chunk's results (the single blocking host
        sync per chunk) and emit its tokens."""
        toks_dev, lps_dev, avs_dev, ais_dev, skeys_dev, running, T = inflight
        finished: List[Request] = []
        # The key mirror rides the batched device_get: a dirty re-upload
        # must not rewind any slot's key stream to a pre-chunk state.
        # Pipelined: a later chunk's dispatch DONATES this chunk's skeys
        # output (is_deleted) — skip the stale sync; the later chunk's own
        # drain supplies the fresh mirror, and a re-upload is always
        # preceded by that drain (dirty state blocks pre-dispatch).
        if skeys_dev.is_deleted():
            toks, lps, avs, ais = jax.device_get(
                (toks_dev, lps_dev, avs_dev, ais_dev)
            )
        else:
            toks, lps, avs, ais, skeys_host = jax.device_get(
                (toks_dev, lps_dev, avs_dev, ais_dev, skeys_dev)
            )
            # only the rows this chunk actually advanced: a request
            # admitted while the chunk was in flight had its key written
            # by prefill AFTER dispatch, and a wholesale copy would rewind
            # it to the pre-admission (zero) snapshot
            for slot in running:
                self._slot_keys[slot] = skeys_host[slot]
        running = dict(running)
        for slot in list(running):
            # aborted between dispatch and drain: its tokens are frozen
            # repeats, and abort already handled the retire
            if running[slot].done:
                del running[slot]
        for t in range(T):
            for slot, req in list(running.items()):
                tok = int(toks[t, slot])
                req.pos += 1
                self._positions[slot] = req.pos
                self._last_tokens[slot] = tok
                self._emit(
                    req, tok, float(lps[t, slot]),
                    [
                        (int(ais[t, slot, j]), float(avs[t, slot, j]))
                        for j in range(avs.shape[2])
                    ]
                    if req.want_top_logprobs
                    else None,
                )
                # keep the budget mirror exact: a dirty re-upload with a
                # stale budget would un-freeze finished slots on device
                self._budgets[slot] = req.max_new_tokens - len(req.out_tokens)
                if req.done:
                    if defer_retire:
                        self._defer_retire(req)
                    else:
                        self._retire(req)
                    finished.append(req)
                    del running[slot]
        return finished

    def _defer_retire(self, req: Request) -> None:
        """A finished request whose pages a still-in-flight chunk may yet
        write: freeze its slot on the next reupload and postpone the page
        free / prefix-cache registration until that chunk drains."""
        self._budgets[req.slot] = 0
        self._dirty = True
        self._pending_retire.append(req)

    def drain_inflight(self) -> None:
        """Complete any dispatched-but-unread decode chunk and flush
        deferred retires. Called before sleep/offload (the results would
        otherwise be lost with the device state). Finished requests are
        NOT returned — they are handed to the next step() call via the
        orphan list, so exactly one consumer (the service loop) resolves
        them."""
        if self._inflight is not None:
            inflight, self._inflight = self._inflight, None
            self._orphan_finished.extend(self._drain_chunk(inflight))
        for r in self._pending_retire:
            self._retire(r)
        self._pending_retire = []

    def has_work(self) -> bool:
        return (
            bool(self._waiting)
            or any(s is not None for s in self._slots)
            or self._inflight is not None
            or bool(self._orphan_finished)
        )

    # -- zero-drain park/resume (engine/parked.py) ---------------------------

    def parked_page_ids(self) -> List[int]:
        """Unique pool page ids a park would page out right now — the
        first ``ceil(pos / page_size)`` pages of every live mid-decode
        request, in order of first use. Shared prefix pages appear once.
        Also the byte basis of the cost oracle's park pricing: the park
        itself gathers exactly this list, so predicted and actual
        page-out bytes agree by construction."""
        out: List[int] = []
        seen: set = set()
        for req in self._slots:
            if req is None or req.done or req.prefilling:
                continue
            used = (
                PageAllocator.pages_needed(req.pos, self.cfg.page_size)
                if req.pos > 0
                else 0
            )
            for p in req.pages[:used]:
                if p not in seen:
                    seen.add(p)
                    out.append(p)
        return out

    def park_requests(self, bucket_bytes: "int | None" = None):
        """Preempt every live and queued request into a host-resident
        :class:`~.parked.ParkedRequests` bundle and drop the KV pool's
        device arrays (``kv_detached``): the engine is then empty — an
        actuation can sleep/swap it without aborting anything, and
        ``resume_parked`` re-seats the bundle bit-exact afterwards.

        Ordering is failure-safe: the KV page-out (fault point
        ``kvsave.d2h``) runs BEFORE any scheduler state is touched, so a
        failed page-out raises with the engine still serving and the
        caller falls back to today's abort path. Returns
        ``(bundle, finished)`` — ``finished`` are requests a pipelined
        drain completed during the quiesce (the caller resolves their
        futures; they were never preempted).

        Mid-prefill (packed) requests are demoted back to the waiting
        queue instead of carrying KV: prefill is a pure function of the
        prompt and no RNG split is consumed before its final segment, so
        re-running it on resume reproduces identical output."""
        from . import parked as parked_mod

        self.drain_inflight()
        live_reqs = [
            r for r in self._slots
            if r is not None and not r.done and not r.prefilling
        ]
        demote = [
            r for r in self._slots
            if r is not None and not r.done and r.prefilling
        ]
        page_ids = self.parked_page_ids()
        k_host = v_host = None
        kv_nbytes = 0
        pageout_s = 0.0
        if page_ids:
            # the faultable transfer, first: nothing below runs unless
            # every live page landed on host. Timed HERE, around the
            # gather alone: the drain/bookkeeping outside it must not
            # anchor the kvsave.d2h bandwidth EWMA low (the sleep.d2h
            # pure-window discipline)
            t0 = time.monotonic()
            k_host, v_host = parked_mod.gather_pages_d2h(
                self.pool, page_ids, bucket_bytes=bucket_bytes,
                span_name="swap.kv_pageout",
            )
            pageout_s = time.monotonic() - t0
            kv_nbytes = int(k_host.nbytes) + int(v_host.nbytes)
        finished = list(self._orphan_finished)
        self._orphan_finished = []
        bundle = parked_mod.ParkedRequests(
            page_ids=page_ids, k_host=k_host, v_host=v_host,
            kv_nbytes=kv_nbytes, pageout_s=pageout_s,
        )
        meta_nbytes = 0
        for r in live_reqs:
            used = PageAllocator.pages_needed(r.pos, self.cfg.page_size)
            pr = parked_mod.ParkedRequest(
                req=r,
                old_pages=list(r.pages[:used]),
                counts_row=np.array(self._token_counts[r.slot], copy=True),
                key_data=np.array(self._slot_keys[r.slot], copy=True),
            )
            meta_nbytes += pr.counts_row.nbytes + pr.key_data.nbytes
            bundle.live.append(pr)
        if self.prefix_cache is not None:
            # refcounts and the hash index die with the pool; resumed
            # pages re-acquire fresh references (the cache restarts cold)
            for r in live_reqs + demote:
                self.prefix_cache.release(r.pages)
            self.prefix_cache.clear()
        for r in demote:
            r.prefilling = False
            r.pos = 0
            r.cached_tokens = 0
            r.shared_pages = 0
            r.pages = []
            r.slot = -1
            r._prefix_hashes = ()
            if hasattr(r, "_blocked_state"):
                del r._blocked_state
            bundle.waiting.append(r)
        for r in live_reqs:
            r.slot = -1
            r.pages = []
        bundle.waiting.extend(self._waiting)
        bundle.nbytes = kv_nbytes + meta_nbytes
        # detach: wipe the scheduler wholesale (the pool and allocator
        # are rebuilt fresh by set_state/rebuild_kv_pool on restore)
        self._slots = [None] * self.cfg.max_batch
        self._waiting = []
        self._page_table[:] = 0
        self._positions[:] = 0
        self._last_tokens[:] = 0
        self._temps[:] = 0.0
        self._topps[:] = 1.0
        self._pres[:] = 0.0
        self._freqs[:] = 0.0
        self._token_counts[:] = 0
        self._budgets[:] = 0
        self._slot_keys[:] = 0
        self._eos_on[:] = 1
        self._bias[:] = 0.0
        self._fresh_slots.clear()
        self._rows_stale = False
        self._dirty = True
        for leaf in self.pool.as_tuple():
            if leaf is not None:
                leaf.delete()
        self.pool.k_pages = None
        self.pool.v_pages = None
        self.kv_detached = True
        return bundle, finished

    def rebuild_kv_pool(self) -> None:
        """Fresh device KV pool + allocator after a zero-drain park
        dropped them (called by the sleeper's set_state when the restored
        state carries no "kv" subtree, and by rollback paths)."""
        m = self._model_cfg
        self.pool = PagePool.create(
            m.num_layers,
            self.cfg.num_pages,
            self.cfg.page_size,
            m.num_kv_heads,
            m.head_dim,
            dtype=m.dtype,
            mesh=self.mesh,
        )
        if self.mesh is None:
            self.pool.replace(
                jax.device_put(self.pool.as_tuple(), jax.devices()[0])
            )
        self.allocator = PageAllocator(self.cfg.num_pages)
        self.kv_detached = False

    def resume_parked(
        self, bundle, bucket_bytes: "int | None" = None
    ) -> Tuple[int, int]:
        """Re-seat a parked bundle into this (awake, empty-pool) engine:
        allocate pages, page the saved KV back in (fault point
        ``kvrestore.h2d``), rewrite page tables through the old->new page
        map (preserving prefix-page sharing between live requests), and
        restore every per-slot mirror — the next dispatch re-uploads the
        whole scheduler state (_dirty), so the resumed decode continues
        bit-exact mid-stream.

        Returns ``(live_resumed, kv_pagein_bytes)``. On a page-in
        failure everything is unwound — allocated pages freed, no slot
        seated, ``bundle.waiting`` re-queued (they carried no KV and lost
        nothing) — and :class:`~.parked.ParkedResumeFailed` is raised so
        the caller aborts the live requests with cause ``state_loss``;
        the engine stays healthy with an empty pool."""
        from . import parked as parked_mod

        if self.kv_detached:
            raise parked_mod.ParkedResumeFailed(
                "resume before the KV pool was rebuilt"
            )
        old2new: Dict[int, int] = {}
        seated: List[tuple] = []
        moved = 0
        try:
            for pr in bundle.live:
                r = pr.req
                need = PageAllocator.pages_needed(
                    len(r.prompt) + r.max_new_tokens, self.cfg.page_size
                )
                new_pages: List[int] = []
                fresh: List[int] = []  # allocated by THIS request
                fresh_old: List[int] = []  # ...and mapped into old2new
                try:
                    for j in range(need):
                        old = (
                            pr.old_pages[j]
                            if j < len(pr.old_pages)
                            else None
                        )
                        if old is not None and old in old2new:
                            new_pages.append(old2new[old])
                            continue
                        got = self._alloc_pages(1)[0]
                        fresh.append(got)
                        if old is not None:
                            old2new[old] = got
                            fresh_old.append(old)
                        new_pages.append(got)
                except BaseException:
                    # free this request's own partial allocation (pages
                    # reused from earlier requests stay theirs; fully
                    # seated requests are unwound by the outer handler)
                    self.allocator.free(fresh)
                    for old in fresh_old:
                        old2new.pop(old, None)
                    raise
                if self.prefix_cache is not None:
                    # one reference per referencing sequence, like
                    # _admit: retire's release then refcounts shared
                    # prefix pages correctly
                    self.prefix_cache.acquire(new_pages)
                seated.append((pr, new_pages))
            if bundle.page_ids:
                pairs = [
                    (i, old2new[p])
                    for i, p in enumerate(bundle.page_ids)
                    if p in old2new
                ]
                moved = parked_mod.scatter_pages_h2d(
                    self.pool, pairs, bundle.k_host, bundle.v_host,
                    bucket_bytes=bucket_bytes,
                    span_name="wake.kv_pagein",
                )
        except BaseException as e:
            for pr, new_pages in seated:
                if self.prefix_cache is not None:
                    self.allocator.free(
                        self.prefix_cache.release(new_pages)
                    )
                else:
                    self.allocator.free(new_pages)
            self._waiting.extend(bundle.waiting)
            self._dirty = True
            raise parked_mod.ParkedResumeFailed(
                f"{type(e).__name__}: {e}"
            ) from e
        # no failure past this point: seating is pure host bookkeeping
        for pr, new_pages in seated:
            r = pr.req
            slot = self._free_slot()
            assert slot is not None, "parked batch exceeded max_batch"
            r.slot = slot
            r.pages = new_pages
            r.shared_pages = 0
            r._prefix_hashes = ()
            self._slots[slot] = r
            row = np.zeros((self.cfg.pages_per_seq,), dtype=np.int32)
            row[: len(new_pages)] = new_pages
            self._page_table[slot] = row
            self._positions[slot] = r.pos
            self._last_tokens[slot] = (
                r.out_tokens[-1] if r.out_tokens else 0
            )
            self._temps[slot] = r.temperature
            self._topps[slot] = r.top_p
            self._pres[slot] = r.presence_penalty
            self._freqs[slot] = r.frequency_penalty
            self._token_counts[slot] = pr.counts_row
            self._budgets[slot] = r.max_new_tokens - len(r.out_tokens)
            self._eos_on[slot] = 0 if r.ignore_eos else 1
            self._bias[slot] = 0.0
            for t, v in r.logit_bias.items():
                self._bias[slot, t] = v
            self._slot_keys[slot] = pr.key_data
        self._waiting = list(bundle.waiting) + self._waiting
        self._dirty = True
        return len(seated), moved

    def abort(self, seq_id: int, reason: str = "aborted") -> bool:
        """Abort one request (client disconnect): waiting requests are
        dropped, in-flight ones retired — their pages return to the pool and
        the slot frees this step instead of decoding to max_new_tokens."""
        for i, req in enumerate(self._waiting):
            if req.seq_id == seq_id:
                self._waiting.pop(i)
                req.done = True
                req.error = reason
                return True
        for req in self._slots:
            if req is not None and req.seq_id == seq_id:
                if req.done:
                    # finished on its own terms, retire merely deferred
                    # (pipelined); deferring again would double-free its
                    # pages — and the legitimate finish must stand
                    return False
                if self._inflight is not None:
                    # an in-flight chunk may still write this slot's pages
                    self._defer_retire(req)
                else:
                    self._retire(req)
                req.done = True
                req.error = reason
                return True
        return False

    def abort_all(self, reason: str) -> List[Request]:
        """Fail every waiting and in-flight request and reset the scheduler
        (slots, page tables, allocator, prefix cache). Used when continuity
        of generation cannot be preserved — e.g. a level-2 sleep discarded
        the KV cache, which also invalidates every cached prefix page."""
        # a dispatched chunk's results are irrelevant (everything aborts);
        # deferred-retire requests still occupy _slots, so the loop below
        # retires them with everyone else
        self._inflight = None
        self._pending_retire = []
        aborted = list(self._waiting)
        self._waiting.clear()
        for req in list(self._slots):
            if req is not None:
                if not req.done:
                    # deferred-retire requests finished on their own terms;
                    # only genuinely in-flight ones get the abort error
                    aborted.append(req)
                self._retire(req)
        for req in aborted:
            req.done = True
            req.error = reason
        if self.prefix_cache is not None:
            # the KV content backing the index is gone: matching a stale
            # chain would silently attend over garbage pages
            self.allocator.free(self.prefix_cache.clear())
        return aborted

    # -- convenience --------------------------------------------------------

    def generate(
        self,
        prompts: Seq[Seq[int]],
        max_new_tokens: int = 16,
        temperature: float = 0.0,
    ) -> List[List[int]]:
        ids = [
            self.add_request(p, max_new_tokens, temperature) for p in prompts
        ]
        results: Dict[int, List[int]] = {}
        while self.has_work():
            for req in self.step():
                results[req.seq_id] = req.out_tokens
        return [results[i] for i in ids]
