"""The serving engine: continuous batching over a paged KV cache.

A single-process engine instance (one per model, spawned by the launcher)
owning sharded params, the page pool, and two compiled programs:

  * ``_prefill_fn``  — batch-1 prompt ingestion, bucketed to power-of-two
    lengths so at most log2(max_seq) prefill programs are ever compiled;
  * ``_step_fn``     — one fused decode+sample step for the whole slot batch,
    cache donated so page updates are in-place in HBM.

Decode runs every slot every step (static shapes; empty slots write to the
reserved null page and their outputs are ignored) — the XLA-friendly version
of continuous batching: requests join/leave by host-side slot bookkeeping,
the compiled step never changes shape.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence as Seq

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..models import llama
from ..parallel.mesh import shard_pytree
from .kv_cache import OutOfPages, PageAllocator, PagePool
from .sampling import sample


@dataclass(frozen=True)
class EngineConfig:
    model: llama.LlamaConfig
    max_batch: int = 8
    page_size: int = 16
    num_pages: int = 2048
    max_seq_len: int = 0  # 0 -> model.max_seq_len
    eos_token_id: int = -1  # -1 = never stop on EOS
    #: Attention implementation: "reference" (pure XLA) or "pallas"
    #: (hand-written TPU kernels; interpreter mode off-TPU).
    attention_impl: str = "reference"

    @property
    def seq_len(self) -> int:
        return self.max_seq_len or self.model.max_seq_len

    @property
    def pages_per_seq(self) -> int:
        return -(-self.seq_len // self.page_size)


@dataclass
class Request:
    seq_id: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    out_tokens: List[int] = field(default_factory=list)
    pages: List[int] = field(default_factory=list)
    pos: int = 0  # tokens in cache
    slot: int = -1
    done: bool = False
    error: Optional[str] = None
    submit_time: float = field(default_factory=time.monotonic)
    first_token_time: Optional[float] = None


class EngineAsleep(RuntimeError):
    """The engine's device state is offloaded; wake_up() before serving."""


class InferenceEngine:
    def __init__(
        self,
        cfg: EngineConfig,
        params: Optional[Dict[str, Any]] = None,
        mesh: Optional[Mesh] = None,
        seed: int = 0,
    ) -> None:
        self.cfg = cfg
        self.mesh = mesh
        # thread the attention impl through the model config (per-engine, not
        # a process global — two engines must not clobber each other)
        m = cfg.model
        if m.attention_impl != cfg.attention_impl:
            import dataclasses

            m = dataclasses.replace(m, attention_impl=cfg.attention_impl)
        if params is None:
            params = llama.init_params(jax.random.key(seed), m)
        if mesh is not None:
            params = shard_pytree(params, mesh, llama.param_logical_axes(m))
        self.params = params
        self.pool = PagePool.create(
            m.num_layers,
            cfg.num_pages,
            cfg.page_size,
            m.num_kv_heads,
            m.head_dim,
            dtype=m.dtype,
            mesh=mesh,
        )
        self.allocator = PageAllocator(cfg.num_pages)
        b, p = cfg.max_batch, cfg.pages_per_seq
        self._page_table = np.zeros((b, p), dtype=np.int32)
        self._positions = np.zeros((b,), dtype=np.int32)
        self._last_tokens = np.zeros((b,), dtype=np.int32)
        self._temps = np.zeros((b,), dtype=np.float32)
        self._slots: List[Optional[Request]] = [None] * b
        self._waiting: List[Request] = []
        self._next_seq_id = 1
        self._rng = jax.random.key(seed + 1)

        model_cfg = m

        def _prefill(params, tokens, seq_lens, cache, page_table):
            logits, cache = llama.prefill(
                params, model_cfg, tokens, seq_lens, cache, page_table
            )
            last = jnp.take_along_axis(
                logits, (seq_lens - 1)[:, None, None], axis=1
            )[:, 0]
            return last, cache

        # cache (arg 3) donated: prefill updates pages in place.
        self._prefill_fn = jax.jit(_prefill, donate_argnums=(3,))

        def _step(params, tokens, positions, cache, page_table, temps, key):
            logits, cache = llama.decode_step(
                params, model_cfg, tokens, positions, cache, page_table
            )
            next_tokens = sample(logits, key, temps)
            return next_tokens, cache

        self._step_fn = jax.jit(_step, donate_argnums=(3,))

    # -- request lifecycle --------------------------------------------------

    def add_request(
        self,
        prompt: Seq[int],
        max_new_tokens: int = 16,
        temperature: float = 0.0,
    ) -> int:
        if not prompt:
            raise ValueError("empty prompt")
        total = len(prompt) + max_new_tokens
        if total > self.cfg.seq_len:
            raise ValueError(
                f"prompt+generation {len(prompt)}+{max_new_tokens} exceeds "
                f"max_seq_len {self.cfg.seq_len}"
            )
        if PageAllocator.pages_needed(total, self.cfg.page_size) > self.cfg.num_pages - 1:
            raise ValueError(
                f"request needs {PageAllocator.pages_needed(total, self.cfg.page_size)} "
                f"pages but the pool only has {self.cfg.num_pages - 1}"
            )
        req = Request(
            seq_id=self._next_seq_id,
            prompt=list(prompt),
            max_new_tokens=max_new_tokens,
            temperature=temperature,
        )
        self._next_seq_id += 1
        self._waiting.append(req)
        return req.seq_id

    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self._slots):
            if s is None:
                return i
        return None

    def _admit(self, req: Request) -> bool:
        slot = self._free_slot()
        if slot is None:
            return False
        total = len(req.prompt) + req.max_new_tokens
        need = PageAllocator.pages_needed(total, self.cfg.page_size)
        try:
            req.pages = self.allocator.alloc(need)
        except OutOfPages:
            return False
        req.slot = slot
        self._slots[slot] = req
        row = np.zeros((self.cfg.pages_per_seq,), dtype=np.int32)
        row[: len(req.pages)] = req.pages
        self._page_table[slot] = row
        return True

    def _prefill_bucket(self, n: int) -> int:
        b = 16
        while b < n:
            b *= 2
        return min(b, self.cfg.seq_len)

    def _run_prefill(self, req: Request) -> None:
        n = len(req.prompt)
        bucket = self._prefill_bucket(n)
        tokens = np.zeros((1, bucket), dtype=np.int32)
        tokens[0, :n] = req.prompt
        seq_lens = np.array([n], dtype=np.int32)
        table = self._page_table[req.slot : req.slot + 1]
        last_logits, cache = self._prefill_fn(
            self.params,
            jnp.asarray(tokens),
            jnp.asarray(seq_lens),
            self.pool.as_tuple(),
            jnp.asarray(table),
        )
        self.pool.replace(cache)
        self._rng, key = jax.random.split(self._rng)
        tok = sample(
            last_logits,
            key,
            jnp.asarray([req.temperature], dtype=jnp.float32),
        )
        first = int(tok[0])
        req.pos = n
        self._emit(req, first)
        self._positions[req.slot] = req.pos  # position of the token to place
        self._last_tokens[req.slot] = first
        self._temps[req.slot] = req.temperature

    def _emit(self, req: Request, token: int) -> None:
        if req.first_token_time is None:
            req.first_token_time = time.monotonic()
        req.out_tokens.append(token)
        if (
            len(req.out_tokens) >= req.max_new_tokens
            or token == self.cfg.eos_token_id
        ):
            req.done = True

    def _retire(self, req: Request) -> None:
        self.allocator.free(req.pages)
        self._slots[req.slot] = None
        self._page_table[req.slot] = 0
        self._positions[req.slot] = 0
        self._last_tokens[req.slot] = 0
        req.slot = -1

    # -- the engine loop body ----------------------------------------------

    def step(self) -> List[Request]:
        """Admit + prefill waiting requests, then one decode step for the
        running batch. Returns requests that finished this step."""
        if self.params is None:
            raise EngineAsleep("engine state is offloaded (sleeping)")
        finished: List[Request] = []

        while self._waiting:
            req = self._waiting[0]
            if not self._admit(req):
                break
            self._waiting.pop(0)
            self._run_prefill(req)
            if req.done:
                self._retire(req)
                finished.append(req)

        running = [r for r in self._slots if r is not None]
        if running:
            self._rng, key = jax.random.split(self._rng)
            next_tokens, cache = self._step_fn(
                self.params,
                jnp.asarray(self._last_tokens),
                jnp.asarray(self._positions),
                self.pool.as_tuple(),
                jnp.asarray(self._page_table),
                jnp.asarray(self._temps),
                key,
            )
            self.pool.replace(cache)
            toks = np.asarray(next_tokens)
            for req in running:
                tok = int(toks[req.slot])
                req.pos += 1
                self._positions[req.slot] = req.pos
                self._last_tokens[req.slot] = tok
                self._emit(req, tok)
                if req.done:
                    self._retire(req)
                    finished.append(req)
        return finished

    def has_work(self) -> bool:
        return bool(self._waiting) or any(s is not None for s in self._slots)

    def abort_all(self, reason: str) -> List[Request]:
        """Fail every waiting and in-flight request and reset the scheduler
        (slots, page tables, allocator). Used when continuity of generation
        cannot be preserved — e.g. a level-2 sleep discarded the KV cache."""
        aborted = list(self._waiting)
        self._waiting.clear()
        for req in list(self._slots):
            if req is not None:
                aborted.append(req)
                self._retire(req)
        for req in aborted:
            req.done = True
            req.error = reason
        return aborted

    # -- convenience --------------------------------------------------------

    def generate(
        self,
        prompts: Seq[Seq[int]],
        max_new_tokens: int = 16,
        temperature: float = 0.0,
    ) -> List[List[int]]:
        ids = [
            self.add_request(p, max_new_tokens, temperature) for p in prompts
        ]
        results: Dict[int, List[int]] = {}
        while self.has_work():
            for req in self.step():
                results[req.seq_id] = req.out_tokens
        return [results[i] for i in ids]
