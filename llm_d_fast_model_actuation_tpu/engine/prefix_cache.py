"""Automatic prefix caching: page-aligned KV reuse across requests.

The reference's engine ships this as vLLM's "automatic prefix caching":
requests sharing a prompt prefix (few-shot headers, system prompts, chat
history) skip prefill compute and KV writes for the shared part. Here it
is page-native: the unit of sharing is one full KV page (`page_size`
tokens), identified by the HASH CHAIN of its token content —
``h_i = H(h_{i-1}, tokens_of_page_i)`` — so a page is only ever matched
under the exact same prefix that produced it.

Ownership model (host-side, like the allocator it extends):
  * an index entry holds ONE reference to its page; every sequence whose
    page table includes the page holds one more;
  * retiring a sequence drops its references — pages that remain only
    cache-referenced stay resident (warm) and join the LRU;
  * allocation pressure evicts LRU **leaf** entries (no cached children)
    and returns their pages to the allocator; parents become leaves as
    children go, so chains unwind from the tail and an entry reachable
    from the index can never lose an ancestor before its descendants.

Safety: a shared page is never written again — suffix prefill scatters
only positions past the cached prefix, and generated tokens land in later
pages (only FULL prompt pages are registered; a page that would also
receive generated tokens is never cached).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple


def _chunk_hash(parent: str, tokens: Sequence[int]) -> str:
    h = hashlib.sha256()
    h.update(parent.encode())
    h.update(b":")
    h.update(",".join(str(t) for t in tokens).encode())
    return h.hexdigest()


@dataclass
class _Entry:
    chain_hash: str
    page_id: int
    parent_hash: str  #: "" for the first page of a prompt
    children: int = 0
    last_used: int = 0


@dataclass
class PrefixCache:
    page_size: int
    #: page_id -> total reference count (index + live sequences)
    _refs: Dict[int, int] = field(default_factory=dict)
    _by_hash: Dict[str, _Entry] = field(default_factory=dict)
    _clock: int = 0
    #: monotonic mutation counter: bumps on register/evict (contents
    #: changed), so blocked-admission memos can't be fooled by refcount
    #: churn that returns sizes to their prior values
    version: int = 0
    #: tokens served from cache instead of prefill (observability)
    hit_tokens: int = 0
    lookups: int = 0
    hits: int = 0

    # -- matching ------------------------------------------------------------

    def match(
        self, prompt: Sequence[int]
    ) -> Tuple[List[int], int, List[str]]:
        """Longest cached page chain for `prompt`. PURE: no stats, no LRU
        bumps — a matched request can still fail admission (OutOfPages)
        and retry every engine step; only `commit` (called once admission
        succeeded) records the hit.

        Returns (shared_page_ids, cached_token_count, chain_hashes); the
        hashes feed `commit`/`register` so the chain is hashed once, not
        three times. Never matches the whole prompt — at least one token
        must remain to prefill (the query that produces the first sampled
        logits).
        """
        ps = self.page_size
        full_pages = (len(prompt) - 1) // ps  # leave >= 1 token to prefill
        pages: List[int] = []
        hashes: List[str] = []
        parent = ""
        for i in range(full_pages):
            h = _chunk_hash(parent, prompt[i * ps : (i + 1) * ps])
            e = self._by_hash.get(h)
            if e is None:
                break
            pages.append(e.page_id)
            hashes.append(h)
            parent = h
        return pages, len(pages) * ps, hashes

    def commit(self, hashes: Sequence[str]) -> None:
        """Record an admitted hit: stats + LRU recency for the matched
        chain entries (`hashes` from the `match` that admitted)."""
        self.lookups += 1
        if not hashes:
            return
        self.hits += 1
        self.hit_tokens += len(hashes) * self.page_size
        self._clock += 1
        for h in hashes:
            e = self._by_hash.get(h)
            if e is None:
                break
            e.last_used = self._clock

    def acquire(self, page_ids: Sequence[int]) -> None:
        """A sequence starts referencing shared pages."""
        for p in page_ids:
            self._refs[p] = self._refs.get(p, 0) + 1

    # -- registration --------------------------------------------------------

    def register(
        self,
        prompt: Sequence[int],
        page_ids: Sequence[int],
        shared_count: int,
        known_hashes: Sequence[str] = (),
    ) -> None:
        """Insert this sequence's FULL prompt pages into the index.

        `page_ids` is the sequence's page-table order (shared prefix pages
        first); the first `shared_count` pages are already cached (their
        chain hashes may be passed via `known_hashes` to skip re-hashing).
        Pages receiving generated tokens later (anything past the last
        full prompt page) are never registered.
        """
        ps = self.page_size
        full_pages = len(prompt) // ps
        parent = ""
        self._clock += 1
        inserted = False
        for i in range(full_pages):
            if i < len(known_hashes):
                h = known_hashes[i]
            else:
                h = _chunk_hash(parent, prompt[i * ps : (i + 1) * ps])
            e = self._by_hash.get(h)
            if e is None:
                if i < shared_count:
                    # ancestor chain was evicted between match and register
                    # (can't happen single-threaded, but stay defensive):
                    # stop rather than re-register a shared page
                    break
                e = _Entry(
                    chain_hash=h,
                    page_id=page_ids[i],
                    parent_hash=parent,
                    last_used=self._clock,
                )
                self._by_hash[h] = e
                self._refs[page_ids[i]] = self._refs.get(page_ids[i], 0) + 1
                if parent:
                    self._by_hash[parent].children += 1
                inserted = True
            else:
                e.last_used = self._clock
            parent = h
        if inserted:
            self.version += 1

    # -- release / eviction --------------------------------------------------

    def release(self, page_ids: Sequence[int]) -> List[int]:
        """A sequence stops referencing pages. Returns the page ids whose
        refcount reached zero — the caller frees those in its allocator
        (pages still index-referenced stay resident)."""
        freed: List[int] = []
        for p in page_ids:
            n = self._refs.get(p)
            if n is None:
                freed.append(p)  # never cache-tracked: plain page
                continue
            if n <= 1:
                del self._refs[p]
                freed.append(p)
            else:
                self._refs[p] = n - 1
        return freed

    def evict(self, want_pages: int) -> List[int]:
        """Drop up to `want_pages` LRU leaf entries whose pages are only
        cache-referenced; returns the page ids now free for reuse.

        One scan builds the initial leaf heap; parents that become leaves
        as their children go are pushed lazily, so an m-page eviction over
        an n-entry index is O(n + m log n), not O(n*m)."""
        import heapq

        freed: List[int] = []
        heap = [
            (e.last_used, e.chain_hash)
            for e in self._by_hash.values()
            if e.children == 0 and self._refs.get(e.page_id, 0) == 1
        ]
        heapq.heapify(heap)
        while heap and len(freed) < want_pages:
            _, h = heapq.heappop(heap)
            e = self._by_hash.get(h)
            # stale heap entries: re-check eligibility at pop time
            if (
                e is None
                or e.children != 0
                or self._refs.get(e.page_id, 0) != 1
            ):
                continue
            del self._by_hash[h]
            del self._refs[e.page_id]
            freed.append(e.page_id)
            if e.parent_hash:
                parent = self._by_hash.get(e.parent_hash)
                if parent is not None:
                    parent.children -= 1
                    if (
                        parent.children == 0
                        and self._refs.get(parent.page_id, 0) == 1
                    ):
                        heapq.heappush(
                            heap, (parent.last_used, parent.chain_hash)
                        )
        if freed:
            self.version += 1
        return freed

    def clear(self) -> List[int]:
        """Drop the whole index (KV content is gone — e.g. a level-2 sleep
        zeroed the pool): returns every page the index alone was keeping
        resident, for the caller's allocator. Call with no live sequences."""
        freed: List[int] = []
        for e in self._by_hash.values():
            n = self._refs.get(e.page_id, 0)
            if n <= 1:
                self._refs.pop(e.page_id, None)
                freed.append(e.page_id)
            else:  # a live holder remains (defensive; callers retire first)
                self._refs[e.page_id] = n - 1
        self._by_hash.clear()
        self.version += 1
        return freed

    # -- introspection -------------------------------------------------------

    def resident_pages(self) -> int:
        return len(self._by_hash)

    def stats(self) -> Dict[str, int]:
        return {
            "resident_pages": self.resident_pages(),
            "lookups": self.lookups,
            "hits": self.hits,
            "hit_tokens": self.hit_tokens,
        }
