"""The TPU-native inference engine stratum.

The reference delegates this entire layer to vLLM (+ CUDA); here it is
in-repo and JAX-native: paged KV cache, continuous batching, jitted
prefill/decode, level-1 sleep/wake (HBM <-> pinned host) and the
engine-agnostic admin API (`/sleep`, `/wake_up`, `/is_sleeping`) the
dual-pods controller speaks.
"""

from .kv_cache import PageAllocator, PagePool  # noqa: F401
from .engine import EngineConfig, InferenceEngine  # noqa: F401
from .model_pool import HostModelPool  # noqa: F401
from .sleep import SleepLevel, SleepManager, swap_states  # noqa: F401
