"""TPU client release / reacquire — the mechanism behind chip time-sharing.

The reference's product premise is that a slept server frees its accelerator
for another server (docs/dual-pods.md:20-56; sleep actuation
inference-server.go:1710-1718). On GPU that falls out of CUDA contexts
coexisting; on TPU it does NOT: a process's PJRT client holds the chip
exclusively (a second process blocks in client init until the first exits).
So a TPU sleep that merely empties HBM still monopolizes the device.

This module tears the PJRT client down *in process* and re-creates it later:

  release_devices()   — drop all compiled-executable caches, then destroy
                        every live backend client. Caller must have deleted /
                        numpy-snapshotted every device array first: after
                        this call any surviving jax.Array is a dangling
                        reference to a dead client.
  reacquire_devices() — re-initialize the backend (jax re-creates the PJRT
                        client on first use) and return the new devices. If
                        another process holds the chip this blocks/retries
                        until it is released — the hardware itself enforces
                        the one-awake-holder invariant the launcher's
                        ChipLedger tracks.

Compiled programs do not survive release (executables are client objects);
wake-path recompiles are served from the persistent XLA compile cache the
launcher arms before forking (launcher/main.py), so re-lowering is a disk
read, not a fresh XLA run.

Sharding objects also die with the client. `sharding_spec` / `rebuild_spec`
round-trip a sharding through a device-free description so state saved
before release can be restored onto the re-created devices (same process,
same device ordering).
"""

from __future__ import annotations

import gc
import logging
import time
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.extend.backend  # submodule is not auto-imported by `import jax`
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec, SingleDeviceSharding

logger = logging.getLogger(__name__)


def release_devices() -> None:
    """Destroy this process's backend clients (all platforms)."""
    # Drop every cached executable first: live LoadedExecutables keep client
    # references, and tracing caches would hand back programs bound to the
    # dead client after re-init.
    jax.clear_caches()
    gc.collect()
    jax.extend.backend.clear_backends()
    gc.collect()
    logger.info("released backend clients (TPU chip is now free)")


def reacquire_devices(
    timeout_s: float = 300.0, poll_s: float = 0.5
) -> Sequence[jax.Device]:
    """Re-create the backend client and return the fresh device list.

    Client init blocks while another process holds the chip; we retry until
    the deadline in case the platform surfaces contention as an error
    instead of a block.
    """
    deadline = time.monotonic() + timeout_s
    last: Optional[Exception] = None
    while time.monotonic() < deadline:
        try:
            devs = jax.devices()
            logger.info("reacquired %d device(s): %s", len(devs), devs)
            return devs
        except Exception as e:  # init failed (chip busy) — retry
            last = e
            time.sleep(poll_s)
    raise TimeoutError(
        f"could not reacquire TPU devices within {timeout_s}s: {last}"
    )


# -- device-free sharding descriptions ---------------------------------------


def sharding_spec(x: jax.Array) -> Tuple[str, Any, Any, Any]:
    """A picklable, device-free description of ``x.sharding``."""
    s = x.sharding
    if isinstance(s, NamedSharding):
        return (
            "named",
            tuple(s.mesh.axis_names),
            tuple(s.mesh.devices.shape),
            tuple(s.spec),
        )
    return ("single", None, None, None)


def _device_array(mesh_shape: Tuple[int, ...]) -> np.ndarray:
    """Device array for a mesh shape, with the SAME ordering policy as
    `parallel.mesh.make_mesh`: topology-aware (`mesh_utils`) on real TPU so
    inner axes stay ICI-adjacent — and therefore identical to the pre-release
    mesh, keeping post-wake executables cache-compatible."""
    n = int(np.prod(mesh_shape))
    devices = jax.devices()[:n]
    if devices[0].platform == "tpu":
        try:
            from jax.experimental import mesh_utils

            return mesh_utils.create_device_mesh(
                tuple(mesh_shape), devices=list(devices)
            )
        except Exception:
            pass  # odd topologies: flat ordering, same as make_mesh fallback
    return np.asarray(devices).reshape(mesh_shape)


def rebuild_spec(spec: Tuple[str, Any, Any, Any]):
    """Rebuild a sharding from `sharding_spec` output on the CURRENT devices."""
    kind, axis_names, mesh_shape, pspec = spec
    if kind == "named":
        return NamedSharding(
            Mesh(_device_array(mesh_shape), axis_names), PartitionSpec(*pspec)
        )
    return SingleDeviceSharding(jax.devices()[0])


def rebuild_mesh(axis_names: Tuple[str, ...], mesh_shape: Tuple[int, ...]) -> Mesh:
    return Mesh(_device_array(mesh_shape), axis_names)
