"""Engine HTTP server: OpenAI-style completions + the sleep/wake admin API.

This is the process the launcher forks (the reference forks `vllm serve` with
VLLM_SERVER_DEV_MODE admin endpoints; here it's our JAX engine). The admin
contract is engine-agnostic and matches what the dual-pods controller speaks
(inference-server.go:1497,1712,1984):

  GET  /health       200 once serving
  GET  /is_sleeping  {"is_sleeping": bool}
  POST /sleep?level=1|2
  POST /wake_up

Inference:
  POST /v1/completions       {"prompt": str | [int], "max_tokens",
                              "temperature", "top_p", "stop",
                              "logprobs", "stream"}
  POST /v1/chat/completions  {"messages": [{role, content}...], ...}
  GET  /v1/models

Both generation endpoints stream OpenAI-style SSE (`data: {json}` per token,
`data: [DONE]` terminator) when `"stream": true`.

The engine loop runs on a dedicated thread (device steps block); HTTP
handlers enqueue requests and await futures. Sleep acquires the step lock, so
it happens on a step boundary with no request in flight on device.
"""

from __future__ import annotations

import argparse
import asyncio
import concurrent.futures
import contextlib
import json
import logging
import os
import shlex
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from aiohttp import web
from prometheus_client import Counter, Gauge, Histogram

from ..models import llama
from ..models.moe import MoeConfig
from ..utils import faults, tracing
from .engine import EngineConfig, InferenceEngine
from .model_pool import HostModelPool
from .sleep import (
    SwapRolledBack,
    SwapRollbackFailed,
    attach_sleep,
    swap_states,
)

logger = logging.getLogger(__name__)

#: Scheduling pressure: waiting + in-flight requests. The HPA's per-pod
#: scaling signal (deploy/hpa/hpa.yaml); labeled by model because two
#: engine instances can share one process in tests.
ENGINE_QUEUE_DEPTH = Gauge(
    "fma_engine_queue_depth",
    "Requests waiting or in flight in this engine",
    ["model"],
)

# Serving observability (the vLLM-equivalent engine metrics an operator
# expects on the engine's /metrics; the reference serves vLLM's):
ENGINE_TTFT = Histogram(
    "fma_engine_time_to_first_token_seconds",
    "Submit to first emitted token",
    ["model"],
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1, 2, 5, 10, 30),
)
ENGINE_E2E_LATENCY = Histogram(
    "fma_engine_request_seconds",
    "Submit to request completion",
    ["model"],
    buckets=(0.05, 0.1, 0.25, 0.5, 1, 2, 5, 10, 30, 60, 120),
)
ENGINE_PROMPT_TOKENS = Counter(
    "fma_engine_prompt_tokens_total", "Prompt tokens processed", ["model"]
)
ENGINE_GENERATED_TOKENS = Counter(
    "fma_engine_generation_tokens_total", "Tokens generated", ["model"]
)
ENGINE_ABORTS = Counter(
    "fma_engine_aborted_requests_total",
    "Requests aborted, by cause: client (disconnect), swap (actuation "
    "preempted queued/in-flight work), state_loss (level-2 wake)",
    ["model", "reason"],
)
# Zero-drain actuation (docs/perf.md "Zero-drain actuation"): instead of
# aborting, --zero-drain parks the victim model's live requests (KV pages
# paged out beside the slept weights) and resumes them bit-exact after the
# wake/swap-back. Every preempted request eventually resolves to exactly
# one outcome; the byte counter is the parked-KV transfer volume.
ENGINE_PREEMPTED = Counter(
    "fma_engine_preempted_requests_total",
    "Requests preempted by a zero-drain actuation, by final outcome "
    "(resumed = re-seated and continued; aborted = parked state lost — "
    "KV restore failure, parked-model eviction, or client disconnect "
    "while parked; migrated = handed off to a sibling instance and "
    "continued there)",
    ["model", "outcome"],  # outcome: resumed | aborted | migrated
)
ENGINE_KV_PAGEOUT = Counter(
    "fma_engine_kv_pageout_bytes_total",
    "Parked-KV bytes moved by zero-drain preempt/resume, by direction "
    "(d2h = page-out at park, h2d = page-in at resume)",
    ["dir"],
)
# Live request migration (docs/operations.md "Draining a node without
# dropping streams"): a zero-drain parked bundle handed to a sibling
# instance over the wire, resumed mid-decode on the destination. Source
# outcomes: committed (fence spent, results proxied) | resumed_local
# (export/import failed, streams continued at home) | state_loss (the
# double-fault degradation). Destination outcomes: imported | rolled_back.
ENGINE_MIGRATIONS = Counter(
    "fma_engine_migrations_total",
    "Live request migrations, by role (source|destination) and terminal "
    "outcome (committed | resumed_local | state_loss | imported | "
    "rolled_back)",
    ["role", "outcome"],
)
ENGINE_MIGRATE_BYTES = Counter(
    "fma_engine_migrate_bytes_total",
    "Parked-bundle KV bytes moved by live request migration, by "
    "direction (export = serialized to the wire on the source, import = "
    "paged into the destination pool)",
    ["dir"],
)
ENGINE_KV_USAGE = Gauge(
    "fma_engine_kv_cache_usage_ratio",
    "Fraction of KV pages in use",
    ["model"],
)
ENGINE_PREFIX_HIT_TOKENS = Gauge(
    "fma_engine_prefix_cache_hit_tokens",
    "Prompt tokens served from the prefix cache instead of prefill",
    ["model"],
)
ENGINE_SPEC_PROPOSED = Gauge(
    "fma_engine_spec_proposed_tokens",
    "Tokens proposed by n-gram speculative decoding",
    ["model"],
)
ENGINE_SPEC_ACCEPTED = Gauge(
    "fma_engine_spec_accepted_tokens",
    "Proposed tokens accepted by the verify forward",
    ["model"],
)

# SLO / goodput telemetry (docs/perf.md "Fleet benchmarking and goodput"):
# the request-lifecycle observables the multi-model scheduler (ROADMAP
# item 1) optimizes and the fleet harness (`bench.py fleet`) reports.
# Queue wait separates "sat behind other work / an actuation" from "the
# prefill itself was slow" inside the existing TTFT histogram.
ENGINE_QUEUE_WAIT = Histogram(
    "fma_engine_queue_wait_seconds",
    "Submit to first scheduled (queue time; prefill excluded)",
    ["model"],
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1, 2, 5, 10, 30),
)
ENGINE_SLO_REQUESTS = Counter(
    "fma_engine_slo_requests_total",
    "Finished requests judged against a configured SLO target "
    "(--slo-ttft-ms / --slo-tpot-ms; one observation per enabled slo)",
    ["model", "slo", "outcome"],  # slo: ttft|tpot, outcome: met|violated
)
ENGINE_GOODPUT_TOKENS = Counter(
    "fma_engine_goodput_tokens_total",
    "Generated tokens from requests that met every configured SLO "
    "(equals generation_tokens_total when no SLO target is set)",
    ["model"],
)
ENGINE_ARRIVAL_RATE = Gauge(
    "fma_engine_request_arrival_rate",
    "EWMA of request arrivals (requests/s) for the resident model — the "
    "demand signal a multi-model scheduler consumes",
    ["model"],
)

# Model hot-swap observability (docs/engine.md "Model hot-swap"): the swap
# is the actuation hot path, so its latency, how much of it overlapped, and
# the transfer window it held are all first-class operator signals.
ENGINE_SWAP_SECONDS = Histogram(
    "fma_engine_swap_seconds",
    "Model hot-swap wall time (labeled by the incoming model)",
    ["model"],
    buckets=(0.05, 0.1, 0.25, 0.5, 1, 2, 5, 10, 30, 60),
)
ENGINE_SWAPS = Counter(
    "fma_engine_swaps_total",
    "Completed model hot-swaps by source of the incoming state",
    ["model", "source"],  # source: pool | cold
)
ENGINE_SWAP_OVERLAP_FRAC = Gauge(
    "fma_engine_swap_overlap_fraction",
    "Fraction of the last swap spent with both DMA directions in flight",
    ["model"],
)
ENGINE_SWAP_INFLIGHT_BYTES = Gauge(
    "fma_engine_swap_peak_bytes_in_flight",
    "Peak transfer bytes in flight during the last swap",
    ["model"],
)
ENGINE_POOL_BYTES = Gauge(
    "fma_engine_model_pool_bytes",
    "Pinned-host bytes held by pooled (slept) models",
)
ENGINE_POOL_MODELS = Gauge(
    "fma_engine_model_pool_models",
    "Models resident in the host model pool",
)
ENGINE_POOL_HITS = Counter(
    "fma_engine_model_pool_hits",
    "Swap-ins served from the host model pool (no checkpoint re-read)",
)
ENGINE_POOL_EVICTIONS = Counter(
    "fma_engine_model_pool_evictions",
    "Pooled models evicted (budget pressure or device release)",
)

# Tiered, content-addressed pool (docs/perf.md "Tiered weight cache and
# delta swap"): per-tier residency, how many host bytes dedup across
# sibling fine-tune variants is saving right now, tier traffic, and how
# much of the last swap crossed the device boundary vs was content-matched
# away.
ENGINE_POOL_TIER_BYTES = Gauge(
    "fma_engine_model_pool_tier_bytes",
    "Bytes resident per model-pool tier (host chunks / disk spill)",
    ["tier"],  # host | disk
)
ENGINE_POOL_TIER_CHUNKS = Gauge(
    "fma_engine_model_pool_tier_chunks",
    "Content-addressed chunks resident per model-pool tier",
    ["tier"],
)
ENGINE_POOL_DEDUP_SAVED = Gauge(
    "fma_engine_model_pool_dedup_saved_bytes",
    "Host bytes saved by content-addressed dedup across pooled models",
)
ENGINE_POOL_TIER_EVENTS = Counter(
    "fma_engine_model_pool_tier_events_total",
    "Chunk-store traffic by event",
    ["event"],  # dedup_hit | host_hit | disk_spill | disk_hit |
    #             disk_eviction | verify_failure | miss
)
ENGINE_SWAP_DELTA_BYTES = Gauge(
    "fma_engine_swap_delta_bytes",
    "Last swap's bytes over the device boundary by kind",
    ["model", "kind"],  # kind: moved | deduped
)

# Compressed actuation (docs/perf.md "Compressed actuation"): cumulative
# wire bytes per transfer mode and direction across every sleep / wake /
# swap edge — the signal for "what is --sleep-quant actually saving".
# A Gauge used as a monotonic accumulator so the exposition name matches
# the documented fma_engine_actuation_bytes{mode,dir} exactly.
ENGINE_ACTUATION_BYTES = Gauge(
    "fma_engine_actuation_bytes",
    "Cumulative actuation transfer bytes by mode and direction",
    ["mode", "dir"],  # mode: off | int8 | fp8; dir: d2h | h2d
)

# Actuation cost oracle + decision flight recorder (docs/operations.md
# "Pricing an actuation"; utils/costs.py): durations next to the byte
# counter above — bytes without seconds can't validate the oracle from
# Prometheus alone — plus the last prediction per kind and how wrong it
# was. The scheduler-brain's cost telemetry (ROADMAP item 1).
ENGINE_ACTUATION_SECONDS = Histogram(
    "fma_engine_actuation_seconds",
    "Actuation wall seconds by kind and phase (phase=d2h/h2d are the "
    "transfer windows; total is the whole verb incl. overlap/commit)",
    ["kind", "phase"],  # kind: swap | sleep | wake; phase: d2h | h2d | total
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1, 2, 5, 10, 30, 60, 120),
)
ENGINE_PREDICTED_BYTES = Gauge(
    "fma_engine_actuation_predicted_bytes",
    "Last actuation's oracle-predicted wire bytes, by kind (compare "
    "against fma_engine_actuation_bytes increments: byte prediction is "
    "deterministic from digests/shapes, so any drift is a bug signal)",
    ["kind"],
)
ENGINE_COST_ERROR = Gauge(
    "fma_engine_cost_prediction_error_ratio",
    "Signed relative error (predicted-actual)/actual of the last "
    "actuation's predicted seconds, by kind — the oracle's live "
    "accuracy score (only set when the prediction used measured "
    "bandwidth)",
    ["kind"],
)

# Self-healing observability (docs/operations.md "Self-healing and fault
# drills"): every recovery edge — a swap failure rolled back in-process, or
# a rollback that itself failed and flipped /health — is counted, so an
# operator can tell "the failure path fired and healed" apart from silence.
ENGINE_RECOVERIES = Counter(
    "fma_engine_recoveries_total",
    "Recovery attempts by path and outcome",
    ["path", "outcome"],  # path: swap | swap_cold; outcome: rolled_back |
    #                       rollback_failed
)

# Cold-start observability (docs/perf.md "Cold-start tuning"): the pipelined
# loader's phase breakdown for the last cold build, and background-prefetch
# outcomes. `phase` is read (disk -> staged host buffers, wall window),
# convert (cumulative casted-copy time inside staging), h2d (first transfer
# issued -> last landed) or total.
ENGINE_COLDLOAD_PHASE_SECONDS = Gauge(
    "fma_engine_coldload_phase_seconds",
    "Last cold weight-load phase timing",
    ["model", "phase"],  # phase: read | convert | h2d | total
)
ENGINE_COLDLOAD_OVERLAP_FRAC = Gauge(
    "fma_engine_coldload_overlap_fraction",
    "Fraction of the last cold load spent with disk read and H2D in flight",
    ["model"],
)
ENGINE_PREFETCHES = Counter(
    "fma_engine_prefetch_total",
    "Background checkpoint prefetches by outcome",
    ["outcome"],  # completed | aborted | failed | rejected
)
ENGINE_PREFETCH_BYTES = Gauge(
    "fma_engine_prefetch_staged_bytes",
    "Host bytes staged by the last completed prefetch",
)

# AOT warmup + executable pool (docs/perf.md "Warmup and the executable
# pool"): first-touch compiles were the tail that wagged TTFT after the
# streaming loaders fixed weight movement — these say whether the compile
# work is riding under transfers (warmup seconds per program) and whether
# rebuilds are reusing executables instead of recompiling (pool traffic).
ENGINE_WARMUP_SECONDS = Gauge(
    "fma_engine_warmup_seconds",
    "AOT warmup compile seconds by program (last warmup)",
    ["program"],
)
ENGINE_EXEC_POOL_HITS = Counter(
    "fma_engine_exec_pool_hits_total",
    "Executable-pool lookups served without compiling",
)
ENGINE_EXEC_POOL_MISSES = Counter(
    "fma_engine_exec_pool_misses_total",
    "Executable-pool lookups that had to compile",
)
ENGINE_EXEC_POOL_EVICTIONS = Counter(
    "fma_engine_exec_pool_evictions_total",
    "Executables evicted from the pool (budget pressure or device release)",
)
ENGINE_EXEC_POOL_BYTES = Gauge(
    "fma_engine_exec_pool_bytes",
    "Estimated host bytes held by pooled executables",
)
ENGINE_EXEC_POOL_ENTRIES = Gauge(
    "fma_engine_exec_pool_entries",
    "Executables resident in the pool",
)

# Mixed-batch (token-packed) serving observability (docs/metrics.md): how
# full the decode batch runs, how densely the packed buffer is used, and
# how much activation padding each dispatch path burns — the occupancy/
# queue signals the multi-model scheduler (ROADMAP item 1) consumes.
ENGINE_SLOT_OCCUPANCY = Gauge(
    "fma_engine_decode_slot_occupancy",
    "Fraction of decode slots occupied by running requests",
    ["model"],
)
ENGINE_PACKED_TOKENS = Histogram(
    "fma_engine_packed_tokens_per_step",
    "Valid (non-padding) tokens packed into each mixed-batch step",
    ["model"],
    buckets=(8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096),
)
ENGINE_PAD_WASTE_BYTES = Counter(
    "fma_engine_prefill_pad_waste_bytes_total",
    "Activation bytes computed for padding tokens, by dispatch path "
    "(bucketed = power-of-two prefill bucket padding; packed = invalid "
    "rows of the mixed [token_budget] buffer)",
    ["model", "path"],
)
ENGINE_STEP_H2D_BYTES = Counter(
    "fma_engine_step_h2d_bytes_total",
    "Host->device scheduler/dispatch bytes moved by engine steps, by "
    "serving path (packed = mixed-program row inputs + a packed "
    "engine's scheduler uploads, which are O(rows) per step at steady "
    "state — the [max_batch, vocab] mirrors re-upload only on dirty "
    "edges; bucketed = prefill/suffix/spec dispatch inputs + a "
    "bucketed engine's scheduler uploads)",
    ["model", "path"],
)

# Co-resident sibling variants (docs/perf.md "Co-resident sibling
# variants"): one shared base tensor set on device plus per-variant
# deltas, routed per request inside the packed step — sibling traffic
# then actuates with zero swaps. The gauges expose the HBM budget's
# live accounting and the dedup the shared base is buying.
ENGINE_RESIDENT_VARIANTS = Gauge(
    "fma_engine_resident_variants",
    "Device-resident model variants (the base model counts as 1)",
)
ENGINE_VARIANT_HBM_BYTES = Gauge(
    "fma_engine_variant_hbm_bytes",
    "Device bytes held by co-resident variant deltas (the "
    "--variant-hbm-mib budget's numerator)",
)
ENGINE_CORESIDENT_SAVED_BYTES = Gauge(
    "fma_engine_coresident_saved_bytes",
    "Device bytes the shared base is saving vs full per-variant "
    "copies (sum over residents of base bytes minus their delta)",
)
ENGINE_RESIDENT_EVENTS = Counter(
    "fma_engine_resident_events_total",
    "Resident-set changes by event",
    ["event"],  # attach | detach | reject
)
ENGINE_ROUTED_REQUESTS = Counter(
    "fma_engine_routed_requests_total",
    "Requests routed per-request to a co-resident variant (label = the "
    "variant model; base-model requests are not counted here)",
    ["model"],
)

MODEL_CONFIGS = {
    "tiny": llama.LlamaConfig.tiny,
    "llama3-8b": llama.LlamaConfig.llama3_8b,
    "llama3-70b": llama.LlamaConfig.llama3_70b,
    "tiny-moe": MoeConfig.tiny_moe,
    "mixtral-8x7b": MoeConfig.mixtral_8x7b,
    "tiny-gemma": llama.LlamaConfig.tiny_gemma,
    "gemma3-4b": llama.LlamaConfig.gemma3_4b,
    "qwen2-7b": lambda: llama.LlamaConfig(
        vocab_size=152064,
        hidden_size=3584,
        num_layers=28,
        num_heads=28,
        num_kv_heads=4,
        head_dim=128,
        intermediate_size=18944,
        rope_theta=1e6,
        max_seq_len=32768,
    ),
    "tinyllama-1.1b": lambda: llama.LlamaConfig(
        vocab_size=32000,
        hidden_size=2048,
        num_layers=22,
        num_heads=32,
        num_kv_heads=4,
        head_dim=64,
        intermediate_size=5632,
        rope_theta=10000.0,
        max_seq_len=2048,
    ),
    "bench-1b": lambda: llama.LlamaConfig(
        vocab_size=32000,
        hidden_size=2048,
        num_layers=24,
        num_heads=16,
        num_kv_heads=8,
        head_dim=128,
        intermediate_size=5632,
        rope_theta=10000.0,
        max_seq_len=2048,
    ),
}


def make_arg_parser() -> argparse.ArgumentParser:
    """The engine's CLI (the `options` string of an instance config is parsed
    with exactly this parser, mirroring how the reference launcher reuses
    vLLM's own parser, launcher.py:871-883)."""
    p = argparse.ArgumentParser(prog="fma-engine", add_help=False)
    p.add_argument("--model", default="tiny", help="model name or config key")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--max-model-len", type=int, default=0)
    p.add_argument("--tensor-parallel-size", type=int, default=1)
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--num-pages", type=int, default=512)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--eos-token-id", type=int, default=-1)
    p.add_argument(
        "--attention-impl",
        default="auto",
        choices=["auto", "reference", "grouped", "pallas"],
        help="decode attention implementation (auto = pallas on TPU, "
        "grouped XLA elsewhere)",
    )
    p.add_argument(
        "--quantization",
        default="",
        choices=["", "int8"],
        help="weight-only quantization (int8 = W8A16 per-output-channel; "
        "halves decode's HBM weight reads)",
    )
    p.add_argument(
        "--prefix-caching",
        default="on",
        choices=["on", "off"],
        help="automatic prefix caching: page-aligned KV reuse across "
        "requests sharing a prompt prefix",
    )
    p.add_argument(
        "--decode-chunk",
        type=int,
        default=0,
        help="max decode steps fused into one compiled dispatch "
        "(0 = auto: 32 on TPU where per-dispatch latency dominates, "
        "8 elsewhere; docs/perf.md)",
    )
    p.add_argument(
        "--pipeline-decode",
        choices=["on", "off"],
        default="off",
        help="double-buffer decode chunks: dispatch chunk k+1 before "
        "reading chunk k (overlaps device compute with host fetch+emit; "
        "token delivery lags one chunk; ignored in gangs)",
    )
    p.add_argument(
        "--drain-tail",
        choices=["auto", "single", "chunk"],
        default="auto",
        help="batch drain tail: single T=1 steps, or one full chunk with "
        "surplus steps frozen in-program (saves up to chunk-1 dispatch "
        "round trips; auto = chunk on TPU, single elsewhere)",
    )
    p.add_argument(
        "--max-prefill-tokens",
        type=int,
        default=0,
        help="chunked prefill: segment prompts longer than this (bounds "
        "prefill memory and compile buckets); 0 = off",
    )
    p.add_argument(
        "--packed-serving",
        choices=["on", "off"],
        default="off",
        help="token-packed mixed-batch serving (docs/perf.md): whenever "
        "prefill work is pending, one compiled program processes a flat "
        "[token-budget] buffer packing prefill segments AND a decode "
        "step per running sequence — concurrent prompts neither "
        "serialize nor stall decode, and the per-bucket prefill "
        "programs leave the warmup plan. off (default) preserves the "
        "bucketed path byte-for-byte. Composes with sharded meshes "
        "(--tensor-parallel-size); incompatible with --pipeline-decode "
        "and multi-host gangs",
    )
    p.add_argument(
        "--token-budget",
        type=int,
        default=0,
        help="row capacity of the packed mixed-batch buffer "
        "(--packed-serving): bounds per-step prefill work like "
        "--max-prefill-tokens bounds segments. 0 = auto (256, floored "
        "so every decode slot plus one prefill block always fits)",
    )
    p.add_argument(
        "--resident-variants",
        type=int,
        default=1,
        help="co-resident sibling variants (docs/perf.md 'Co-resident "
        "sibling variants'): maximum model variants simultaneously "
        "device-resident, the base model included — N > 1 enables "
        "POST /v1/residents (attach a sibling's changed leaves next to "
        "the shared base tensors) and per-request model routing inside "
        "the packed step, so sibling traffic actuates with zero swaps. "
        "1 (default) keeps the one-resident engine byte-for-byte. "
        "Requires --packed-serving on; incompatible with multi-host "
        "gangs and --quantization",
    )
    p.add_argument(
        "--variant-hbm-mib",
        type=int,
        default=1024,
        help="device byte budget (MiB) for co-resident variant deltas "
        "(--resident-variants): an attach whose delta would exceed it "
        "is REJECTED to the existing swap path (409), never OOMs the "
        "serving engine",
    )
    p.add_argument(
        "--speculative-ngram",
        type=int,
        default=0,
        help="n-gram (prompt-lookup) speculative decoding: verify up to N "
        "proposed tokens per forward on the single-sequence greedy path; "
        "0 = off",
    )
    p.add_argument(
        "--logprobs-topk",
        type=int,
        default=5,
        help="top-k alternative logprobs computed per token inside the "
        "compiled programs (OpenAI logprobs/top_logprobs; 0 disables)",
    )
    p.add_argument(
        "--slo-ttft-ms",
        type=float,
        default=0.0,
        help="TTFT SLO target in milliseconds (submit -> first token). "
        "Finished requests are judged against it "
        "(fma_engine_slo_requests_total{slo=ttft}) and only SLO-met "
        "requests count toward fma_engine_goodput_tokens_total "
        "(docs/perf.md 'Fleet benchmarking and goodput'); 0 disables",
    )
    p.add_argument(
        "--slo-tpot-ms",
        type=float,
        default=0.0,
        help="time-per-output-token SLO target in milliseconds (mean "
        "inter-token time after the first token); judged per finished "
        "request like --slo-ttft-ms; 0 disables",
    )
    p.add_argument(
        "--trace-requests",
        type=float,
        default=0.0,
        help="head-sampling fraction [0, 1] for per-request lifecycle "
        "traces (request.* span family, docs/tracing.md): each sampled "
        "request's queue/prefill/decode/preempt/migrate legs are "
        "retained in a dedicated trace ring served by GET /v1/traces. "
        "Independent of the fraction, SLO-violated, aborted, and "
        "migrated requests always keep their spans (tail-keep). "
        "0 (default) disables per-request tracing entirely — the "
        "serving hot path stays byte-identical",
    )
    p.add_argument(
        "--arrival-ewma-tau-s",
        type=float,
        default=30.0,
        help="time constant (seconds) of the request arrival-rate EWMA "
        "(fma_engine_request_arrival_rate): the demand signal's memory — "
        "shorter reacts faster to bursts, longer smooths them",
    )
    p.add_argument(
        "--zero-drain",
        choices=["on", "off"],
        default="off",
        help="preempt, page out, and resume live requests across model "
        "hot-swaps and level-1 sleeps instead of aborting them "
        "(docs/perf.md 'Zero-drain actuation'): the victim model's live "
        "KV pages are paged to host beside its slept weights "
        "(byte-counted against --model-pool-mib) and the streams resume "
        "mid-decode bit-exact on wake/swap-back. off (default) keeps "
        "today's abort path byte-for-byte. Multi-host gangs are "
        "rejected; level-2 and device-releasing sleeps keep their "
        "existing semantics",
    )
    p.add_argument(
        "--sleep-release-devices",
        default="auto",
        choices=["auto", "always", "never"],
        help="tear down the TPU client on sleep so other instances can use "
        "the chip (auto = on for TPU, off elsewhere)",
    )
    p.add_argument(
        "--model-pool-mib",
        type=int,
        default=4096,
        help="pinned-host byte budget (MiB) for the slept-model pool "
        "backing POST /v1/swap: models swapped out stay host-resident up "
        "to this budget so swapping back re-reads no checkpoint; 0 "
        "disables pooling (every swap-in is a cold build)",
    )
    p.add_argument(
        "--pool-disk-dir",
        default="",
        help="local-disk spill tier below the host model pool: weight "
        "chunks whose last pooled reference is evicted spill here "
        "(atomic rename, content-verified reload), so a swap back to an "
        "evicted model rebuilds from local disk instead of re-reading "
        "its checkpoint. Defaults to FMA_POOL_SPILL_DIR; empty disables "
        "the tier",
    )
    p.add_argument(
        "--pool-disk-mib",
        type=int,
        default=4096,
        help="byte budget (MiB) for the model pool's disk spill tier "
        "(LRU beyond it); 0 disables the tier",
    )
    p.add_argument(
        "--content-hash",
        default="on",
        choices=["on", "off"],
        help="content-address pooled weights (sha256 per leaf, computed "
        "once at load): dedupes sibling fine-tune variants in the host "
        "pool and lets hot-swaps move only the delta between models "
        "sharing tensors. Sharded single-process meshes participate "
        "with mesh-qualified digests (content hash + mesh shape + "
        "per-leaf sharding spec); ignored (off) for multi-host gangs "
        "and --quantization engines",
    )
    p.add_argument(
        "--sleep-quant",
        default="off",
        choices=["off", "int8", "fp8"],
        help="compressed actuation transfers (docs/perf.md): level-1 "
        "sleep offloads eligible weight stacks as int8 (per-channel "
        "scales) or fp8 (e4m3), only the payload crosses PCIe, and wake "
        "dequantizes on device — ~2x models per GiB of host pool and "
        "~half the transfer bytes per actuation. OPT-IN AND LOSSY-ONCE: "
        "the first quantized offload rounds the weights; every later "
        "cycle reproduces the same bits. off (default) keeps every "
        "sleep/wake/swap bit-exact. Composes with single-process "
        "--tensor-parallel-size meshes (shard-local quant/dequant on "
        "device); multi-host gangs are rejected",
    )
    p.add_argument(
        "--sleep-quant-hot-head",
        default="on",
        choices=["on", "off"],
        help="keep the 'hot head' (embeddings + final norm + lm_head) at "
        "full precision under --sleep-quant (the numerics-conservative "
        "default); off also quantizes embed/lm_head for maximum byte "
        "savings",
    )
    p.add_argument(
        "--swap-bucket-mib",
        type=int,
        default=256,
        help="transfer bucket size (MiB) for chunked sleep/wake and "
        "overlapped hot-swap: bounds peak extra HBM and the in-flight "
        "DMA window to ~one bucket per direction",
    )
    p.add_argument(
        "--exec-pool-mib",
        type=int,
        default=256,
        help="host byte budget (MiB) for the AOT executable pool "
        "(engine/exec_pool.py): compiled prefill/suffix/decode programs "
        "are pooled across swaps keyed by (config hash, mesh, dtype, "
        "bucket), so a rebuild of a previously-seen model recompiles "
        "nothing; 0 disables pooling (warmed executables still install "
        "into the engine being built)",
    )
    p.add_argument(
        "--warmup-buckets",
        default="",
        help="comma-separated prefill token buckets to AOT-precompile "
        "concurrently with swap/prefetch weight transfers (rounded up to "
        "the engine's power-of-two buckets; also warms the suffix-prefill "
        "and decode-chunk programs). Empty disables warmup — first-touch "
        "jit compile, the pre-existing behavior (docs/perf.md)",
    )
    p.add_argument(
        "--load-workers",
        type=int,
        default=0,
        help="parallel shard readers for cold HF weight loads "
        "(0 = auto: min(8, cpu count)); shard reads and dtype casts "
        "release the GIL, so readers genuinely overlap (docs/perf.md "
        "Cold-start tuning)",
    )
    p.add_argument(
        "--load-inflight-mib",
        type=int,
        default=512,
        help="bytes-in-flight bound (MiB) for the streaming cold loader's "
        "host->device transfers: buffers stream to HBM as they complete, "
        "double-buffered in ~half-this-size buckets",
    )
    p.add_argument(
        "--prefetch-mib-s",
        type=int,
        default=0,
        help="I/O throttle (MiB/s) for background checkpoint prefetch "
        "(POST /v1/prefetch) so staging the predicted next model never "
        "starves serving traffic; 0 = unthrottled",
    )
    p.add_argument(
        "--faults",
        default="",
        help="arm fault-injection points at startup (utils/faults.py), "
        'e.g. "swap.h2d=fail:1,coldload.read=delay:0.25" — the '
        "deterministic failure-drill knob; also armable via FMA_FAULTS "
        "env and POST /v1/faults",
    )
    p.add_argument(
        "--tokenizer",
        default="",
        help="HF tokenizer directory (text prompts, chat templates, stop "
        "strings, response text). Defaults to the hf: model directory when "
        "it ships tokenizer files; otherwise a byte-level fallback",
    )
    p.add_argument(
        "--checkpoint-dir",
        default="",
        help="load weights from this Orbax checkpoint (and reload from it "
        "on level-2 wake) instead of random init",
    )
    # Multi-host slice coordination (parallel/multihost.py): N engine
    # processes — one per host — form one jax.distributed job. Defaults
    # come from the FMA_NUM_PROCESSES / FMA_PROCESS_ID /
    # FMA_COORDINATOR_ADDRESS env the gang coordinator ships.
    p.add_argument("--num-processes", type=int, default=0)
    p.add_argument("--process-id", type=int, default=-1)
    p.add_argument("--coordinator-address", default="")
    return p


def resolve_distributed(args: argparse.Namespace) -> Optional[Dict[str, Any]]:
    """CLI flags > gang env > single-process default. Returns kwargs for
    jax.distributed.initialize, or None when single-process."""
    num = args.num_processes or int(os.environ.get("FMA_NUM_PROCESSES", "0") or 0)
    if num <= 1:
        return None
    pid = (
        args.process_id
        if args.process_id >= 0
        else int(os.environ.get("FMA_PROCESS_ID", "-1"))
    )
    addr = args.coordinator_address or os.environ.get(
        "FMA_COORDINATOR_ADDRESS", ""
    )
    if pid < 0 or pid >= num or not addr:
        raise ValueError(
            f"multi-host engine needs process-id in [0,{num}) and a "
            f"coordinator address (got id={pid}, addr={addr!r})"
        )
    return {
        "coordinator_address": addr,
        "num_processes": num,
        "process_id": pid,
    }


def validate_parsed_args(args: argparse.Namespace) -> None:
    if args.model.startswith("hf:"):
        # Hugging Face model directory (models/hf.py). Existence is checked
        # at engine start, not parse time: the controller validates options
        # strings on hosts that don't mount the model volume.
        if not args.model[3:]:
            raise ValueError("--model hf: needs a directory path")
    elif args.model not in MODEL_CONFIGS:
        raise ValueError(
            f"unknown model {args.model!r}; known: {sorted(MODEL_CONFIGS)} "
            "or hf:<model-dir>"
        )
    if args.tensor_parallel_size < 1:
        raise ValueError("--tensor-parallel-size must be >= 1")
    if args.decode_chunk < 0:
        raise ValueError("--decode-chunk must be >= 1, or 0 for auto")
    if args.max_prefill_tokens < 0:
        raise ValueError("--max-prefill-tokens must be >= 0")
    if args.speculative_ngram < 0:
        raise ValueError("--speculative-ngram must be >= 0")
    if getattr(args, "token_budget", 0) < 0:
        raise ValueError("--token-budget must be >= 0, or 0 for auto")
    if getattr(args, "packed_serving", "off") == "on":
        if getattr(args, "pipeline_decode", "off") == "on":
            raise ValueError(
                "--packed-serving is incompatible with --pipeline-decode "
                "(a packed step would race the in-flight chunk)"
            )
        gang = getattr(args, "num_processes", 0) or int(
            os.environ.get("FMA_NUM_PROCESSES", "0") or 0
        )
        if gang > 1:
            raise ValueError(
                "--packed-serving is incompatible with multi-host gangs "
                "(the per-step packing layout is too large for the "
                "lockstep control frame); sharded single-process meshes "
                "via --tensor-parallel-size compose fine"
            )
    if getattr(args, "resident_variants", 1) < 1:
        raise ValueError("--resident-variants must be >= 1")
    if getattr(args, "variant_hbm_mib", 0) < 0:
        raise ValueError("--variant-hbm-mib must be >= 0")
    if getattr(args, "resident_variants", 1) > 1:
        if getattr(args, "packed_serving", "off") != "on":
            raise ValueError(
                "--resident-variants > 1 requires --packed-serving on: "
                "per-request variant routing lives inside the packed "
                "mixed-batch step (the bucketed programs always run "
                "base params)"
            )
        if getattr(args, "quantization", ""):
            raise ValueError(
                "--resident-variants > 1 is incompatible with "
                "--quantization (variant deltas are content-matched "
                "against full-precision leaf digests)"
            )
        if getattr(args, "content_hash", "on") != "on":
            raise ValueError(
                "--resident-variants > 1 requires --content-hash on: "
                "the shared-base/delta split IS the digest diff"
            )
        gang = getattr(args, "num_processes", 0) or int(
            os.environ.get("FMA_NUM_PROCESSES", "0") or 0
        )
        if gang > 1:
            raise ValueError(
                "--resident-variants > 1 is not supported for "
                "multi-host gangs (the lockstep frame has no variant "
                "dimension)"
            )
    if getattr(args, "slo_ttft_ms", 0.0) < 0:
        raise ValueError("--slo-ttft-ms must be >= 0 (0 = off)")
    if getattr(args, "slo_tpot_ms", 0.0) < 0:
        raise ValueError("--slo-tpot-ms must be >= 0 (0 = off)")
    if getattr(args, "arrival_ewma_tau_s", 30.0) <= 0:
        raise ValueError("--arrival-ewma-tau-s must be > 0")
    if not 0.0 <= getattr(args, "trace_requests", 0.0) <= 1.0:
        raise ValueError("--trace-requests must be in [0, 1]")
    if getattr(args, "model_pool_mib", 0) < 0:
        raise ValueError("--model-pool-mib must be >= 0")
    if getattr(args, "swap_bucket_mib", 1) < 1:
        raise ValueError("--swap-bucket-mib must be >= 1")
    sq = getattr(args, "sleep_quant", "off") or "off"
    if sq != "off":
        from ..models import quant as transfer_quant

        reason = transfer_quant.transfer_quant_supported(sq)
        if reason:
            raise ValueError(f"--sleep-quant {sq}: {reason}")
        if getattr(args, "quantization", ""):
            raise ValueError(
                "--sleep-quant composes with full-precision serving only: "
                "a --quantization int8 engine already holds (and moves) "
                "int8 weights"
            )
        if (
            getattr(args, "num_processes", 0)
            or int(os.environ.get("FMA_NUM_PROCESSES", "0") or 0)
        ) > 1:
            raise ValueError(
                "--sleep-quant is not supported for multi-host gangs "
                "(gang offloads stage per-shard and reassemble "
                "bit-for-bit); single-process --tensor-parallel-size "
                "meshes compose fine"
            )
    if getattr(args, "zero_drain", "off") == "on":
        gang = getattr(args, "num_processes", 0) or int(
            os.environ.get("FMA_NUM_PROCESSES", "0") or 0
        )
        if gang > 1:
            raise ValueError(
                "--zero-drain is not supported for multi-host gangs "
                "(parked request bundles are process-local; gang "
                "actuation keeps today's abort semantics)"
            )
    if getattr(args, "pool_disk_mib", 0) < 0:
        raise ValueError("--pool-disk-mib must be >= 0")
    if getattr(args, "exec_pool_mib", 0) < 0:
        raise ValueError("--exec-pool-mib must be >= 0")
    from .exec_pool import parse_warmup_buckets

    parse_warmup_buckets(getattr(args, "warmup_buckets", ""))
    if getattr(args, "load_workers", 0) < 0:
        raise ValueError("--load-workers must be >= 0 (0 = auto)")
    if getattr(args, "load_inflight_mib", 1) < 1:
        raise ValueError("--load-inflight-mib must be >= 1")
    if getattr(args, "prefetch_mib_s", 0) < 0:
        raise ValueError("--prefetch-mib-s must be >= 0 (0 = unthrottled)")
    if getattr(args, "faults", ""):
        try:
            faults.parse_spec(args.faults)
        except ValueError as e:
            raise ValueError(f"--faults: {e}")
    if args.port <= 0 or args.port > 65535:
        raise ValueError(f"invalid port {args.port}")


def parse_engine_options(options: str) -> argparse.Namespace:
    args, unknown = make_arg_parser().parse_known_args(shlex.split(options or ""))
    if unknown:
        raise ValueError(f"unknown engine options: {unknown}")
    validate_parsed_args(args)
    return args


class ProfileConflict(Exception):
    """POST /v1/profile while a capture is running (jax.profiler is
    process-global: exactly one concurrent capture), or DELETE with none."""


class ResidentRejected(Exception):
    """POST /v1/residents admission rejection (cap or --variant-hbm-mib
    budget) or a detach refused while the variant still has live work —
    surfaced as 409, the explicit reject-to-swap-path contract: the
    caller falls back to the existing swap verb, the engine never OOMs
    chasing one more co-resident."""


class MigrationRejected(Exception):
    """A migration verb's precondition failed with nothing displaced —
    identity mismatch, co-resident variants attached, no capacity,
    spent fence token (the double-resume refusal) — surfaced as 409:
    the orchestrator picks another destination or leaves the streams
    where they are."""


class MigrationFailed(Exception):
    """A migration step failed AFTER recovery ran: export failure with
    the streams resumed locally, import failure with the destination
    rolled back clean, or an injected lost ack. Surfaced as 500; the
    fence makes the orchestrator's retry safe."""


class _RateEWMA:
    """Exponentially-decayed event rate (events/second).

    Each arrival adds ``1/tau`` and the estimate decays by
    ``exp(-dt/tau)`` between observations, so a Poisson stream of rate
    lambda converges to lambda regardless of scrape cadence — and the
    estimate keeps decaying toward zero after traffic stops (reading is
    side-effect free on the event count). Not thread-safe; callers hold
    the service's SLO lock."""

    def __init__(self, tau_s: float = 30.0) -> None:
        self.tau_s = max(1e-6, float(tau_s))
        self._rate = 0.0
        self._t: Optional[float] = None

    def _decay(self, now: float) -> None:
        if self._t is None:
            self._t = now
            return
        dt = now - self._t
        if dt > 0:
            import math

            self._rate *= math.exp(-dt / self.tau_s)
            self._t = now

    def observe(self, now: float) -> None:
        self._decay(now)
        self._rate += 1.0 / self.tau_s

    def rate(self, now: float) -> float:
        self._decay(now)
        return self._rate


def _pool_key(model: str, checkpoint_dir: str) -> str:
    """Identity of a pooled model: the same model name restored from a
    different checkpoint is a different set of weights."""
    return f"{model}@{checkpoint_dir}" if checkpoint_dir else model


@dataclass
class _PrefetchedWeights:
    """A pool entry staged by background prefetch (POST /v1/prefetch):
    host-resident plain numpy weights in cfg.dtype — no engine, no device
    state, no compiled programs. A swap to it skips the checkpoint read
    (source="pool") and only pays compile + the H2D stream; eviction is
    just dropping the reference."""

    model_id: str
    checkpoint_dir: str
    params_host: Optional[Dict[str, Any]]
    nbytes: int
    #: flat weight key -> content digest (engine/chunk_store.py): what the
    #: tiered pool dedupes on; carried into the runtime a swap builds
    digests: Optional[Dict[str, str]] = None
    #: --sleep-quant staging: params_host leaves are int8/fp8 payloads and
    #: this is the aligned TransferQuant-or-None list (models/quant.py) —
    #: the consuming swap streams payloads and dequantizes on device
    quant_metas: Optional[list] = None
    quant_mode: str = "off"


@dataclass
class _ModelRuntime:
    """Everything model-specific the service owns: swapping models means
    swapping this bundle. A pooled (slept) runtime keeps its engine object
    — and with it the compiled programs, which are host-resident — so a
    swap-back recompiles nothing and re-reads no checkpoint."""

    model_id: str
    engine: InferenceEngine
    sleeper: Any
    tokenizer: Any
    hf_dir: str
    checkpoint_dir: str
    #: flat weight key -> content digest, computed once at load (None for
    #: random-init/sharded/quantized builds): drives the delta-swap's
    #: device-array reuse and the pool's cross-variant dedup
    digests: Optional[Dict[str, str]] = None
    #: zero-drain actuation (engine/parked.py): the ParkedRequests bundle
    #: this runtime's preempted live work was paged into — stored with
    #: the slept weights, byte-counted against the pool budget, resumed
    #: on wake/swap-back (None = nothing parked)
    parked: Optional[Any] = None


class EngineService:
    """Thread-hosted engine with an async-facing submit/sleep/swap API."""

    def __init__(self, args: argparse.Namespace) -> None:
        self.args = args
        self._lock = threading.Lock()  # serializes device work vs sleep edges
        #: admin calls (sleep/wake/swap) waiting on the step lock: the
        #: engine loop re-acquires it hot (back-to-back steps), which can
        #: starve a parked waiter for a whole generation — the loop yields
        #: briefly when this is non-zero so the admin op lands promptly.
        #: Counter updates are guarded: a lost update from two racing
        #: admin calls would leave it non-zero (or negative) forever.
        self._admin_waiting = 0
        self._admin_count_lock = threading.Lock()
        self._new_work = threading.Event()
        self._stop = False
        self._futures: Dict[int, concurrent.futures.Future] = {}
        self._fut_seq: Dict[int, int] = {}  # id(future) -> seq_id
        self._pending: List[Any] = []
        self._abort_q: List[Any] = []  # futures whose client went away
        self.failure: Optional[str] = None
        #: a recoverable failure happened and was healed in-process (e.g.
        #: a rolled-back swap): /health stays 200 but reports DEGRADED
        #: with this reason until the next successful admin edge clears it
        self.degraded: Optional[str] = None
        #: last-mirrored engine pad-waste byte totals per dispatch path —
        #: the engine keeps cumulative ints, Prometheus wants increments
        self._pad_waste_seen: Dict[str, int] = {}
        self._step_h2d_seen: Dict[str, int] = {}
        self.started_at = time.monotonic()
        # Request-lifecycle SLO/goodput accounting (docs/perf.md "Fleet
        # benchmarking and goodput"): targets in seconds (0 = off), plain
        # counters mirrored into Prometheus and served whole by GET
        # /v1/stats — the one-call instance row the launcher's fleet
        # rollup aggregates. Guarded by _slo_mu: submit() runs on the
        # event loop, _observe_finished on the engine thread, stats() on
        # executor threads.
        self._slo_ttft_s = max(0.0, getattr(args, "slo_ttft_ms", 0.0)) / 1e3
        self._slo_tpot_s = max(0.0, getattr(args, "slo_tpot_ms", 0.0)) / 1e3
        self._slo_mu = threading.Lock()
        self._slo_met = 0
        self._slo_violated = 0
        self._goodput_tokens = 0
        self._generated_tokens = 0
        self._finished_requests = 0
        #: per-cause abort counts (client | swap | state_loss), the
        #: /v1/stats mirror of fma_engine_aborted_requests_total
        self._aborted: Dict[str, int] = {}
        #: actuation edges this process performed (swap | sleep | wake):
        #: with uptime, the fleet rollup's actuations/hour
        self._actuations: Dict[str, int] = {}
        # Zero-drain actuation (docs/perf.md "Zero-drain actuation"):
        # preempt/park/resume counters mirrored into /v1/stats. Guarded
        # by _slo_mu like the rest of the lifecycle accounting.
        self._zero_drain = getattr(args, "zero_drain", "off") == "on"
        self._zd_preempted = 0
        self._zd_resumed = 0
        self._zd_aborted = 0
        self._zd_parked_bytes = 0
        self._zd_migrated = 0
        # Live request migration (ROADMAP item 3a; docs/operations.md
        # "Draining a node without dropping streams"). Source side: at
        # most ONE in-flight export — the fenced bundle awaiting the
        # import ack — plus the set of spent fence tokens (single-use:
        # a spent token can neither release nor locally resume again,
        # which is what makes double-resume a 409, never a duplicate
        # stream). Destination side: stored import acks keyed by fence
        # token (a lost-ack retry replays the stored response instead
        # of seating twice) and the claim table the source's result
        # watchers poll. Counters are _slo_mu-guarded like the rest.
        self._migration: Optional[Dict[str, Any]] = None
        self._migration_gen = 0
        self._spent_fences: set = set()
        self._import_acks: Dict[str, Dict[str, Any]] = {}
        self._imported_claims: Dict[str, Dict[str, Any]] = {}
        self._mig = {
            "exported": 0, "imported": 0, "committed": 0,
            "resumed_local": 0, "rolled_back": 0, "state_loss": 0,
            "requests_out": 0, "requests_in": 0,
            "bytes_out": 0, "bytes_in": 0,
        }
        # Request-lifecycle tracing (docs/tracing.md "request.* spans"):
        # head-sampling fraction applied at submit; tail-keep (violated /
        # aborted / migrated) decided at completion. The exemplar deque
        # pairs each retained violation with its leg breakdown so
        # /v1/stats can answer "which leg" without a trace fetch.
        self._trace_frac = max(
            0.0, min(1.0, getattr(args, "trace_requests", 0.0) or 0.0)
        )
        tracing.configure_request_sampling(self._trace_frac)
        self._slo_exemplars: deque = deque(
            maxlen=int(os.environ.get("FMA_SLO_EXEMPLARS", "16") or 16)
        )
        # Migrated-away streams whose client is still attached: id(fut)
        # -> {"dest", "claim"}, registered when the claim watcher starts
        # and popped (idempotently) on every watcher exit path. This is
        # what lets a client disconnect AFTER migration resolve to
        # exactly one abort on each instance (the source counts
        # reason="client" here; the destination counts its own when the
        # claim-abort notification lands).
        self._proxied: Dict[int, Dict[str, Any]] = {}
        self._arrival = _RateEWMA(
            getattr(args, "arrival_ewma_tau_s", 30.0) or 30.0
        )
        # Actuation cost oracle + decision flight recorder
        # (utils/costs.py; docs/operations.md "Pricing an actuation"):
        # per-kind bandwidth EWMAs fed by every transfer path
        # (sleep/wake/swap windows via the SleepManager's on_transfer
        # hook, cold loads via LoadStats.transfer_figures) — surviving
        # across actuations here — plus the bounded ring of
        # predicted-vs-actual records GET /v1/actuations serves.
        from ..utils.costs import CostBook

        self.costs = CostBook(
            capacity=int(
                os.environ.get("FMA_FLIGHT_RECORDER_CAP", "512") or 512
            )
        )
        # Fault-injection arming (utils/faults.py): env first, then the
        # flag — both before the first build so coldload points can fire
        # on the initial model too.
        faults.load_env()
        if getattr(args, "faults", ""):
            faults.arm_spec(args.faults)

        dist = resolve_distributed(args)
        if dist is not None and args.tensor_parallel_size <= 1:
            # an unsharded multi-process engine would device_put onto
            # non-addressable global devices; the gang contract is SPMD
            # over the whole slice
            raise ValueError(
                "multi-host engine requires --tensor-parallel-size equal "
                "to the global chip count (got "
                f"{args.tensor_parallel_size})"
            )
        if dist is not None:
            # Must run before any device/backend touch: every process of the
            # gang joins the coordination service, and jax.devices() becomes
            # the GLOBAL device set. initialize() blocks until all
            # num_processes join — so this engine reporting healthy implies
            # the whole multi-host gang formed.
            import jax

            if "cpu" in (os.environ.get("JAX_PLATFORMS") or "").lower():
                # The XLA CPU client ships WITHOUT cross-process
                # collectives by default: a CPU gang forms, then the first
                # sharded device_put dies with "Multiprocess computations
                # aren't implemented on the CPU backend" (the leader exits
                # 1, the follower aborts on the lost coordinator). The
                # gloo backend jaxlib bundles makes CPU gangs real — the
                # e2e multihost tests and any CPU rehearsal of a TPU
                # topology depend on it. TPU runs never enter here.
                try:
                    jax.config.update(
                        "jax_cpu_collectives_implementation", "gloo"
                    )
                except Exception:  # noqa: BLE001 — gloo-less jaxlib
                    logger.warning(
                        "this jaxlib has no CPU collectives backend; "
                        "a multi-process CPU gang will fail at the first "
                        "sharded computation"
                    )
            jax.distributed.initialize(**dist)
        # Multi-host lockstep roles (engine/multihost.py): process 0 leads
        # (serves + broadcasts control frames); others follow (replay).
        self.process_id = dist["process_id"] if dist else 0
        self.is_follower = dist is not None and self.process_id > 0
        #: any member of a multi-host gang (leader included): gangs never
        #: carry AOT executables — their scheduler arrays keep the legacy
        #: uncommitted placement (engine._sched_sharding), so a warmed
        #: executable's replicated-NamedSharding avals could never match,
        #: and a leader-AOT/follower-jit split would desync the lockstep
        self.is_gang = dist is not None
        self.watchdog = None
        hb_timeout = float(
            os.environ.get("FMA_GANG_HEARTBEAT_TIMEOUT", "20") or 0
        )
        if dist is not None and hb_timeout > 0:
            # Data-plane failure detection (engine/multihost.py): a dead
            # gang member must become a non-zero exit on every other
            # member within the timeout — collectives can't unwind a
            # wedged lockstep in-process. FMA_GANG_HEARTBEAT_TIMEOUT=0
            # disables (tests that kill members deliberately).
            # Started HERE — right after jax.distributed.initialize,
            # before any checkpoint load — so members heartbeat (and
            # answer probes) through the whole engine init: cross-host
            # init skew from one host cold-loading a multi-GB checkpoint
            # no longer burns FMA_GANG_JOIN_GRACE and tears down a
            # healthy forming gang. The grace now only has to cover the
            # distributed client forming itself.
            from .multihost import GangWatchdog

            self.watchdog = GangWatchdog(
                process_id=self.process_id,
                num_processes=dist["num_processes"],
                coordinator_address=dist["coordinator_address"],
                timeout=hb_timeout,
                join_grace=float(
                    os.environ.get("FMA_GANG_JOIN_GRACE", "60") or 60
                ),
            )
            self.watchdog.start()
        # Tiered host model pool + chunked-transfer sizing (docs/engine.md
        # "Model hot-swap", docs/perf.md "Tiered weight cache and delta
        # swap"): models swapped out stay host-resident up to the budget —
        # content-addressed so sibling fine-tunes dedupe their shared
        # tensors and swaps between them move only the delta — with a
        # local-disk spill tier below for evicted models' chunks.
        # Content hashing covers single-device AND single-process tp
        # meshes: sharded entries carry mesh-qualified digests (the
        # content hash shard-qualified with mesh shape + per-leaf
        # sharding spec — chunk_store.qualify_digest), computed from the
        # same per-process host views the sleeper stages, so sibling
        # variants on one mesh dedupe and delta-swap exactly like
        # single-device ones. Off for multi-host gangs (no host-resident
        # global trees to hash) and for --quantization engines (the
        # serving tree's {"q","s"} leaves have no stable identity).
        self._content_hash = (
            getattr(args, "content_hash", "on") == "on"
            and dist is None
            and not getattr(args, "quantization", "")
        )
        # Compressed actuation transfers (docs/perf.md "Compressed
        # actuation"): opt-in int8/fp8 sleep/wake/swap via models/quant.py.
        # Single-process only — gang offloads stage per-shard bit-for-bit.
        self._sleep_quant = getattr(args, "sleep_quant", "off") or "off"
        self._sleep_quant_hot_head = (
            getattr(args, "sleep_quant_hot_head", "on") != "off"
        )
        if self._sleep_quant != "off" and dist is not None:
            raise ValueError(
                "--sleep-quant is not supported for multi-host gangs"
            )
        from .chunk_store import ChunkStore, default_disk_dir

        chunks = None
        if self._content_hash:
            chunks = ChunkStore(
                disk_dir=getattr(args, "pool_disk_dir", "")
                or default_disk_dir(),
                disk_budget_bytes=max(0, getattr(args, "pool_disk_mib", 4096))
                << 20,
                on_event=self._pool_tier_event,
            )
        self.model_pool = HostModelPool(
            budget_bytes=max(0, getattr(args, "model_pool_mib", 4096)) << 20,
            chunks=chunks,
        )
        self._swap_bucket_bytes = (
            max(1, getattr(args, "swap_bucket_mib", 256)) << 20
        )
        # Co-resident sibling variants (docs/perf.md "Co-resident sibling
        # variants"): model_id -> {handle, nbytes, tier, keys, attached_at}
        # for every variant attached via POST /v1/residents. The base
        # model is NOT an entry here — it is variant handle 0 by
        # construction. Guarded by _lock (attach/detach hold it around
        # the device edge, same discipline as swap).
        self._residents: Dict[str, Dict[str, Any]] = {}
        #: variant handle -> model id (the engine thread's label lookup
        #: for per-model metrics on finished routed requests)
        self._variant_models: Dict[int, str] = {}
        self._resident_variants_cap = max(
            1, int(getattr(args, "resident_variants", 1) or 1)
        )
        self._variant_hbm_budget = (
            max(0, int(getattr(args, "variant_hbm_mib", 0) or 0)) << 20
        )
        #: device-tier refcounts for shared base leaves vs per-variant
        #: deltas (engine/model_pool.py ResidentSetLedger): feeds the
        #: coresident saved-bytes gauge and the launcher's ledger row
        from .model_pool import ResidentSetLedger

        self.resident_ledger = ResidentSetLedger()
        # AOT executable pool + warmup plan (engine/exec_pool.py): compiled
        # programs pooled beside the host model pool, with spill into the
        # launcher's persistent compile-cache dir so entries survive
        # instance restarts (docs/perf.md "Warmup and the executable pool").
        from .exec_pool import (
            ExecutablePool,
            default_spill_dir,
            parse_warmup_buckets,
        )

        self._warmup_buckets = parse_warmup_buckets(
            getattr(args, "warmup_buckets", "")
        )
        self.exec_pool = ExecutablePool(
            budget_bytes=max(0, getattr(args, "exec_pool_mib", 256)) << 20,
            spill_dir=default_spill_dir(),
            on_event=self._exec_pool_event,
        )
        #: the most recent WarmupTask (observability + tests: abort-on-
        #: cancellation and hidden-compile accounting are asserted on it)
        self._last_warmup: Optional[Any] = None
        #: cold runtime builds (checkpoint / HF read or random init); a
        #: pool hit on swap does NOT increment it — the zero-re-read
        #: contract the swap e2e test pins
        self.builds_total = 0
        self.last_swap: Dict[str, Any] = {}
        #: filled by every _build_runtime (h2d_s / bytes_in / buckets_in /
        #: overlap): what a pool-miss swap reports instead of zeros
        self._last_build_stats: Dict[str, Any] = {}
        # Background checkpoint prefetch (POST /v1/prefetch): one staging
        # thread at a time, host-only, abortable.
        self._prefetch_mu = threading.Lock()
        self._prefetch_thread: Optional[threading.Thread] = None
        self._prefetch_abort = threading.Event()
        self.last_prefetch: Dict[str, Any] = {"state": "idle"}
        # On-demand deep profiling (POST/DELETE /v1/profile): one
        # concurrent jax.profiler capture per process.
        self._profile_mu = threading.Lock()
        self._profile_dir: Optional[str] = None
        # Release-on-sleep is resolved BEFORE the first build: zero-drain
        # parking is off for device-releasing sleeps (the park's host
        # bundle survives, but the restore contract is the full-state
        # numpy staging path), and the built engine's zero_drain_park
        # flag — which pricing peeks read — depends on this answer.
        import jax  # deliberately not module-level: parse-time must not touch a backend

        mode = getattr(args, "sleep_release_devices", "auto")
        self.release_on_sleep = (
            mode == "always"
            or (mode == "auto" and jax.default_backend() == "tpu")
        )
        if dist is not None:
            # gang sleep is offload-only: device release would require
            # every process to drop and re-join the distributed client in
            # lockstep (engine/sleep.py raises on it)
            self.release_on_sleep = False
        if self._zero_drain and dist is not None:
            raise ValueError(
                "--zero-drain is not supported for multi-host gangs"
            )
        # The startup span parents on FMA_TRACEPARENT when the spawning
        # launcher stamped one (utils/tracing.py): the child's initial
        # build joins the create-instance trace across the fork.
        with tracing.span(
            "engine.start",
            parent=tracing.env_context(),
            model=args.model,
            pid=os.getpid(),
        ):
            self._install_runtime(
                self._build_runtime(
                    args.model, getattr(args, "checkpoint_dir", "") or ""
                )
            )
        # first flight-recorder row: the initial cold build — trigger
        # "restart" when a supervising launcher re-spawned this child
        # (launcher/instance.py stamps FMA_RESTARTED around the fork), so
        # the recorder distinguishes crash-loop churn from client-driven
        # actuation
        self._record_actuation(
            "coldload",
            args.model,
            trigger=(
                "restart" if os.environ.get("FMA_RESTARTED") else "startup"
            ),
            tier="cold",
            pred=None,
            actual_bytes=self._last_build_stats.get("bytes_in", 0),
            actual_s=self._last_build_stats.get("h2d_s", 0.0),
        )
        if dist is not None and not self.is_follower:
            from .multihost import LockstepLeader

            self.engine.lockstep = LockstepLeader(self.engine)
        self._publisher = self._make_publisher()
        self._publish_usage()
        self._thread = threading.Thread(
            target=self._run_follower if self.is_follower else self._run,
            daemon=True,
            name="engine-loop",
        )
        self._thread.start()

    def _count_abort(self, cause: str, n: int = 1) -> None:
        """One abort-accounting choke point: the Prometheus counter's
        ``reason`` label and the /v1/stats mirror move together, so the
        fleet harness can attribute SLO violations to actuation
        preemption (swap/state_loss) vs client behavior."""
        if n <= 0:
            return
        ENGINE_ABORTS.labels(model=self.args.model, reason=cause).inc(n)
        with self._slo_mu:
            self._aborted[cause] = self._aborted.get(cause, 0) + n

    def _bump_actuation(self, kind: str) -> None:
        with self._slo_mu:
            self._actuations[kind] = self._actuations.get(kind, 0) + 1

    def _abort_engine_work(
        self, reason: str, exc: Exception, cause: str = "state_loss"
    ) -> int:
        """Abort everything waiting or in flight in the engine and fail the
        matching futures (state-loss edges: level-2 wake, model swap).
        Caller holds the step lock."""
        aborted = self.engine.abort_all(reason)
        self._count_abort(cause, len(aborted))
        now = time.monotonic()
        for req in aborted:
            self._finish_request_trace(
                req, now, aborted=True, outcome=cause
            )
            fut = self._futures.pop(req.seq_id, None)
            if fut is not None:
                self._fut_seq.pop(id(fut), None)
                if not fut.done():
                    fut.set_exception(exc)
        return len(aborted)

    def _free_pooled(self, victims, why: str) -> None:
        """Release evicted pool entries' pinned-host bytes: escalating the
        slept runtime to level 2 is exactly 'drop the host copy'."""
        ENGINE_POOL_EVICTIONS.inc(len(victims))
        for victim in victims:
            rt = victim.runtime
            bundle = getattr(rt, "parked", None)
            if bundle is not None:
                # the parked requests' KV dies with the evicted entry:
                # resolve them to a clean state_loss abort, never a
                # future that hangs forever
                rt.parked = None
                self._abort_parked_bundle(
                    bundle,
                    getattr(rt, "model_id", self.args.model),
                    f"preempted requests lost: parked model evicted "
                    f"({why})",
                )
            if isinstance(rt, _PrefetchedWeights):
                # staged host numpy: dropping the reference IS the free
                rt.params_host = None
                continue
            try:
                rt.sleeper.sleep(2)
            except Exception:
                logger.warning(
                    "failed to free pooled model %s (%s)",
                    victim.model_id, why, exc_info=True,
                )

    def _pool_tier_event(self, kind: str) -> None:
        """Mirror chunk-store tier traffic into Prometheus (the store
        never imports prometheus)."""
        ENGINE_POOL_TIER_EVENTS.labels(event=kind).inc()

    def _pool_park(
        self, key: str, runtime: Any, nbytes: int
    ) -> List[Any]:
        """Pool a runtime (or staged-weights bundle) under `key`,
        interning its digested weight leaves into the content-addressed
        chunk store first — so a sibling variant already pooled shares its
        common tensors instead of duplicating them, and an eviction later
        leaves a manifest the disk tier can serve. Returns the evicted
        entries (the caller frees them via _free_pooled)."""
        chunk_digests: List[str] = []
        interned = 0
        weight_digests = None
        if self._content_hash and self.model_pool.budget_bytes > 0:
            from ..models import quant as transfer_quant

            if isinstance(runtime, _PrefetchedWeights):
                if runtime.quant_metas is not None:
                    # quantized staging: payloads intern under TRANSFER
                    # digests (disjoint space — a payload must never be
                    # handed out as the fp tensor it approximates), with
                    # no eviction manifest; "q:" digests spill to disk
                    # like fp chunks — the spill header's content hash
                    # makes the reload verifiable
                    if runtime.params_host is not None:
                        qmap = transfer_quant.transfer_digest_map(
                            runtime.params_host,
                            runtime.quant_metas,
                            prefix="",
                        )
                        (
                            runtime.params_host,
                            chunk_digests,
                            interned,
                        ) = self.model_pool.intern_tree(
                            runtime.params_host, qmap, prefix=""
                        )
                elif runtime.digests and runtime.params_host is not None:
                    (
                        runtime.params_host,
                        chunk_digests,
                        interned,
                    ) = self.model_pool.intern_tree(
                        runtime.params_host, runtime.digests, prefix=""
                    )
                    weight_digests = runtime.digests
            else:
                digests = getattr(runtime, "digests", None)
                host_state = getattr(runtime.sleeper, "_host_state", None)
                quant_metas = getattr(runtime.sleeper, "_quant_meta", None)
                if quant_metas is not None and host_state is not None:
                    # quantized slept runtime: quantized leaves under
                    # disk-spillable "q:" transfer digests, untouched
                    # hot-head leaves under their fp digests (correct
                    # content — they dedupe AND spill with fp siblings);
                    # no eviction manifest
                    qmap = transfer_quant.transfer_digest_map(
                        host_state, quant_metas, prefix="params"
                    )
                    merged = dict(qmap)
                    for k, d in (digests or {}).items():
                        if k not in merged:
                            merged[k] = d
                    (
                        new_tree,
                        chunk_digests,
                        interned,
                    ) = self.model_pool.intern_tree(
                        host_state, merged, prefix="params"
                    )
                    runtime.sleeper._host_state = new_tree
                elif digests and host_state is not None:
                    (
                        new_tree,
                        chunk_digests,
                        interned,
                    ) = self.model_pool.intern_tree(
                        host_state, digests, prefix="params"
                    )
                    runtime.sleeper._host_state = new_tree
                    weight_digests = digests
        if not chunk_digests:
            # nothing interned (e.g. TPU pinned-host staging, whose jax
            # arrays are client-owned): an eviction manifest would be
            # guaranteed-dead — every chunk a miss — and would only crowd
            # resolvable manifests out of the bounded registry
            weight_digests = None
        return self.model_pool.put(
            key,
            runtime,
            nbytes=nbytes,
            chunk_digests=chunk_digests,
            weight_digests=weight_digests,
            interned_bytes=interned,
        )

    def _exec_pool_event(self, kind: str) -> None:
        """Mirror executable-pool traffic into Prometheus (the pool itself
        never imports prometheus)."""
        if kind == "hit":
            ENGINE_EXEC_POOL_HITS.inc()
        elif kind == "miss":
            ENGINE_EXEC_POOL_MISSES.inc()
        elif kind == "eviction":
            ENGINE_EXEC_POOL_EVICTIONS.inc()

    def _start_warmup(
        self, model_id: str, resolved: Optional[tuple] = None
    ) -> Optional[Any]:
        """Kick the AOT warmup task for an incoming `model_id` (None =
        warmup disabled or unsupported): resolves the incoming config
        exactly like the build will and starts compiling on a background
        thread (engine/exec_pool.py). Callers that already ran
        ``_resolve_model`` pass its tuple as ``resolved`` — the resolve
        loads the tokenizer from disk, which must not run twice on the
        swap critical path. Never raises — warmup must never fail a swap;
        worst case the build falls back to first-touch jit."""
        if not self._warmup_buckets:
            return None
        if self.is_gang:
            # no gang member carries AOT entries — followers replay the
            # leader's dispatches through jit, and a leader running AOT
            # programs against follower jit recompiles could desync the
            # lockstep (see is_gang)
            return None
        try:
            if resolved is None:
                resolved = self._resolve_model(model_id)
            model_cfg, eos, extra_eos = resolved[0], resolved[1], resolved[2]
            cfg = self._engine_cfg_for(model_cfg, eos, extra_eos)
            from .exec_pool import WarmupTask

            mesh = None
            if self.args.tensor_parallel_size > 1:
                # the same mesh the build will construct (Mesh equality
                # is by devices + axis names, so the warmed executables'
                # NamedSharding avals match the built engine's arrays)
                from ..parallel.mesh import serving_mesh

                mesh = serving_mesh(self.args.tensor_parallel_size)
            task = WarmupTask(
                cfg,
                self._warmup_buckets,
                pool=self.exec_pool,
                mesh=mesh,
                trace_parent=tracing.current_context(),
                on_program=lambda program, secs: ENGINE_WARMUP_SECONDS.labels(
                    program=program
                ).set(secs),
            )
            self._last_warmup = task
            return task
        except Exception:  # noqa: BLE001 — warmup is strictly best-effort
            logger.warning(
                "AOT warmup start failed for %s", model_id, exc_info=True
            )
            return None

    def _reinstall_executables(self) -> int:
        """Wake re-validates the executable pool instead of recompiling:
        pool entries for the engine's config (including spill reloads,
        where reload is trusted) are reinstalled into the engine's AOT
        table; anything missing jit-compiles on first touch through the
        persistent cache — the pre-existing wake behavior."""
        if not self._warmup_buckets or self.is_gang:
            return 0
        from .exec_pool import exec_key, exec_signature, mesh_shape, warmup_plan

        eng = self.engine
        try:
            sig = exec_signature(eng.cfg, mesh_shape(eng.mesh))
        except Exception:  # noqa: BLE001 — revalidation is best-effort
            return 0
        n = 0
        for program, bucket in warmup_plan(eng.cfg, self._warmup_buckets):
            if (program, bucket) in eng._aot:
                continue
            compiled = self.exec_pool.get(exec_key(sig, program, bucket))
            if compiled is not None:
                eng.install_executable(program, bucket, compiled)
                n += 1
        return n

    @contextlib.contextmanager
    def _admin_lock(self):
        """The step lock, for admin edges (sleep/wake/swap): registers as a
        waiter so the engine loop hands the lock over between steps instead
        of re-acquiring it hot (an unfair lock can otherwise starve the
        admin call until the whole running generation finishes)."""
        with self._admin_count_lock:
            self._admin_waiting += 1
        try:
            with self._lock:
                yield
        finally:
            with self._admin_count_lock:
                self._admin_waiting -= 1

    # -- model runtimes (build / install / hot-swap) -------------------------

    def _resolve_model(self, model_id: str):
        """Config + tokenizer + eos identity for `model_id` — shared by
        the cold build AND the AOT warmup driver, which must derive the
        SAME program shapes (the decode-chunk program embeds the eos id,
        so a divergent resolution would compile the wrong program).
        Returns (model_cfg, eos_token_id, extra_eos, hf_dir, tokenizer)."""
        args = self.args
        hf_dir = ""
        eos_token_id = args.eos_token_id
        extra_eos: tuple = ()
        if model_id.startswith("hf:"):
            from ..models import hf as hf_models

            hf_dir = model_id[3:]
            model_cfg = hf_models.config_from_hf(
                hf_dir, quantization=args.quantization or ""
            )
            if eos_token_id < 0:
                all_eos = hf_models.eos_token_ids_from_hf(hf_dir)
                if all_eos:
                    # Llama-3-Instruct style multi-eos: chat turns end
                    # with <|eot_id|>, not the primary eos
                    eos_token_id = all_eos[0]
                    extra_eos = tuple(all_eos[1:])
        else:
            model_cfg = MODEL_CONFIGS[model_id]()
            if args.quantization and model_cfg.quantization != args.quantization:
                import dataclasses

                model_cfg = dataclasses.replace(
                    model_cfg, quantization=args.quantization
                )
        from . import tokenizer as tokenizer_mod

        tok_path = getattr(args, "tokenizer", "") or ""
        if (
            not tok_path
            and hf_dir
            and tokenizer_mod.has_tokenizer_files(hf_dir)
        ):
            tok_path = hf_dir
        tokenizer = tokenizer_mod.load_tokenizer(tok_path)
        if eos_token_id < 0 and hf_dir:
            # last resort: the tokenizer knows its eos even when neither
            # config.json nor generation_config.json declares one
            eos_token_id = (
                tokenizer.eos_token_id
                if tokenizer.eos_token_id is not None
                else -1
            )
        return model_cfg, eos_token_id, extra_eos, hf_dir, tokenizer

    def _engine_cfg_for(
        self, model_cfg, eos_token_id: int, extra_eos: tuple
    ) -> EngineConfig:
        """The EngineConfig a runtime for `model_cfg` gets — one
        definition, so the warmup driver's AOT compiles and the engine's
        lazy jit always describe the same programs."""
        args = self.args
        import jax  # deliberately not module-level: parse-time must not touch a backend

        return EngineConfig(
            model=model_cfg,
            max_batch=args.max_batch,
            page_size=args.page_size,
            num_pages=args.num_pages,
            max_seq_len=args.max_model_len or 0,
            eos_token_id=eos_token_id,
            extra_eos_ids=extra_eos,
            attention_impl=args.attention_impl,
            decode_chunk=args.decode_chunk
            or (32 if jax.default_backend() == "tpu" else 8),
            pipeline_decode=(
                getattr(args, "pipeline_decode", "off") == "on"
            ),
            drain_tail=getattr(args, "drain_tail", "auto"),
            prefix_caching=args.prefix_caching == "on",
            max_prefill_tokens=args.max_prefill_tokens,
            speculative_ngram=args.speculative_ngram,
            logprobs_topk=max(0, getattr(args, "logprobs_topk", 5)),
            packed_serving=(
                getattr(args, "packed_serving", "off") == "on"
            ),
            token_budget=getattr(args, "token_budget", 0),
        )

    def _qualify_digests(
        self, digests: Optional[Dict[str, str]], model_cfg
    ) -> Optional[Dict[str, str]]:
        """Shard-qualify a flat content-digest map for this engine's mesh
        placement (no-op single-device): each digest becomes
        ``m:<hash(tp|spec)>:<content>`` (chunk_store.qualify_digest) with
        the per-leaf sharding spec derived from the MODEL CONFIG's
        logical axes — the same rule table shard_pytree places with — so
        the host-only prefetch staging path and the placed build qualify
        identically, and a digest can only ever match content under the
        same mesh shape AND the same per-leaf spec. Idempotent on
        already-qualified maps (tier manifests carried through
        take_staged)."""
        tp = self.args.tensor_parallel_size
        if not digests or tp <= 1:
            return digests
        from ..models.registry import logical_axes_for
        from ..parallel.mesh import flat_spec_strs
        from .chunk_store import qualify_digest

        specs = flat_spec_strs(logical_axes_for(model_cfg))
        missing = [k for k in digests if k not in specs]
        if missing:
            # a digest key with no logical-axes entry qualifies with an
            # empty spec — still tp-qualified, and swap's sharding
            # equality check keeps matches safe, but the "re-sharded
            # leaf never matches by digest" guarantee is weakened for
            # these leaves: surface the key drift instead of hiding it
            logger.warning(
                "content digests have no sharding spec for %d leaves "
                "(digest keys drifted from the model's logical axes?): %s",
                len(missing), sorted(missing)[:8],
            )
        return {
            k: qualify_digest(d, f"tp={tp}|{specs.get(k, '')}")
            for k, d in digests.items()
        }

    def _build_runtime(
        self,
        model_id: str,
        checkpoint_dir: str = "",
        staged_params: Optional[Dict[str, Any]] = None,
        warmup: Optional[Any] = None,
        resolved: Optional[tuple] = None,
        staged_digests: Optional[Dict[str, str]] = None,
        staged_quant: Optional[list] = None,
    ) -> _ModelRuntime:
        """Traced wrapper around the cold build: the `with` form ends the
        span (stamping the error) even when the build raises — the
        cold-swap failure path must not leak an open span."""
        with tracing.span(
            "engine.build_runtime",
            model=model_id,
            checkpoint_dir=checkpoint_dir,
            staged=staged_params is not None,
        ):
            return self._build_runtime_impl(
                model_id, checkpoint_dir, staged_params, warmup, resolved,
                staged_digests, staged_quant,
            )

    def _build_runtime_impl(
        self,
        model_id: str,
        checkpoint_dir: str = "",
        staged_params: Optional[Dict[str, Any]] = None,
        warmup: Optional[Any] = None,
        resolved: Optional[tuple] = None,
        staged_digests: Optional[Dict[str, str]] = None,
        staged_quant: Optional[list] = None,
    ) -> _ModelRuntime:
        """Cold-build an awake runtime for `model_id`: config -> tokenizer
        -> params (checkpoint / HF read, or random init) -> engine ->
        sleeper. Pool hits on a slept runtime bypass this entirely;
        `staged_params` (a prefetched host tree) skips the checkpoint read
        and streams straight host -> device. Leaves the build's transfer
        accounting in `_last_build_stats` so a pool-miss swap can report
        its real H2D cost.

        ``warmup`` (a WarmupTask kicked before the transfer started) is
        joined AFTER the weights land and its executables installed into
        the new engine — the build completes with warm weights AND warm
        executables, compile having ridden under the DMA. ``resolved`` is
        an already-computed ``_resolve_model`` tuple (the swap path
        resolves once and shares it with the warmup kick)."""
        args = self.args
        if resolved is None:
            resolved = self._resolve_model(model_id)
        model_cfg, eos_token_id, extra_eos, hf_dir, tokenizer = resolved
        mesh = None
        if args.tensor_parallel_size > 1:
            from ..parallel.mesh import serving_mesh

            mesh = serving_mesh(args.tensor_parallel_size)
        # Build transfer accounting: a pool-miss swap moves the whole
        # incoming model to HBM inside this build, and the swap metrics
        # must say so (h2d seconds/bytes were reported as 0 before).
        build_stats: Dict[str, Any] = {
            "h2d_s": 0.0,
            "bytes_in": 0,
            "buckets_in": 0,
            "overlap_s": 0.0,
            "overlap_frac": 0.0,
        }
        inflight = max(1, getattr(args, "load_inflight_mib", 512)) << 20
        params = None
        #: per-leaf content digests for the new runtime, computed once at
        #: load (or carried through from a prefetch/tier staging) — the
        #: tiered pool's and the delta-swap's weight identity
        digests: Optional[Dict[str, str]] = staged_digests
        t_load0 = time.monotonic()
        if checkpoint_dir and staged_params is None:
            from ..models import checkpoint

            ckpt_stats: Dict[str, Any] = {}
            params = checkpoint.load_params(
                checkpoint_dir, model_cfg, mesh=mesh, stats_out=ckpt_stats
            )
            if self._content_hash:
                digests = ckpt_stats.get("digests") or None
            # Orbax restores each leaf straight into its device placement:
            # the restore wall IS the cold H2D window (read inseparable)
            build_stats["h2d_s"] = ckpt_stats.get(
                "restore_s", time.monotonic() - t_load0
            )
            import jax as _jax

            self.costs.observe_transfer(
                "coldload.h2d",
                sum(x.nbytes for x in _jax.tree.leaves(params)),
                build_stats["h2d_s"],
            )
        elif hf_dir or staged_params is not None:
            from ..models import hf as hf_models

            lstats = hf_models.LoadStats()
            if staged_params is not None:
                # prefetched host weights: no disk read, just the stream in
                params = hf_models.place_staged_params(
                    staged_params, model_cfg, mesh=mesh,
                    max_inflight_bytes=inflight, stats=lstats,
                )
                if staged_quant is not None:
                    # quantized staging (--sleep-quant prefetch): the
                    # placement streamed int8/fp8 payloads (half the PCIe
                    # bytes); expand to serving precision on device,
                    # aligned by flatten order with the staged tree
                    import jax

                    from ..models import quant as transfer_quant

                    leaves, treedef = jax.tree.flatten(params)
                    if len(staged_quant) != len(leaves):
                        # fail LOUD: serving raw int8 payloads as weights
                        # would be silent garbage, never a slow path
                        raise RuntimeError(
                            "quantized staging metadata does not align "
                            f"with the placed tree ({len(staged_quant)} "
                            f"metas vs {len(leaves)} leaves)"
                        )
                    payloads = []
                    for i, meta in enumerate(staged_quant):
                        if meta is None:
                            continue
                        payloads.append(leaves[i])
                        leaves[i] = transfer_quant.dequantize_leaf(
                            leaves[i], meta
                        )
                    params = jax.tree.unflatten(treedef, leaves)
                    params = jax.block_until_ready(params)
                    for p in payloads:
                        p.delete()
            else:
                # pipelined cold load: parallel shard readers + streaming
                # placement straight into the serving sharding
                params = hf_models.load_params(
                    hf_dir, model_cfg, mesh=mesh,
                    workers=getattr(args, "load_workers", 0) or None,
                    max_inflight_bytes=inflight, stats=lstats,
                    want_digests=self._content_hash,
                )
                if self._content_hash:
                    digests = dict(lstats.digests) or None
                for phase, v in (
                    ("read", lstats.read_s),
                    ("convert", lstats.convert_s),
                    ("h2d", lstats.h2d_s),
                    ("total", lstats.total_s),
                ):
                    ENGINE_COLDLOAD_PHASE_SECONDS.labels(
                        model=model_id, phase=phase
                    ).set(v)
                ENGINE_COLDLOAD_OVERLAP_FRAC.labels(model=model_id).set(
                    lstats.overlap_frac
                )
            build_stats.update(
                h2d_s=lstats.h2d_s,
                buckets_in=lstats.buckets_h2d,
                overlap_s=lstats.overlap_s,
                overlap_frac=lstats.overlap_frac,
            )
            for kind, b, s in lstats.transfer_figures():
                self.costs.observe_transfer(kind, b, s)
        import jax  # deliberately not module-level: parse-time must not touch a backend

        engine = InferenceEngine(
            self._engine_cfg_for(model_cfg, eos_token_id, extra_eos),
            params=params,
            mesh=mesh,
            seed=args.seed,
        )
        if params is None:
            # random init lands on device inside engine construction: the
            # whole build window is device-state creation
            build_stats["h2d_s"] = time.monotonic() - t_load0
        build_stats["bytes_in"] = sum(
            x.nbytes
            for x in jax.tree.leaves(
                {"p": engine.params, "kv": engine.pool.as_tuple()}
            )
        )
        if warmup is not None:
            # The transfer is over: join the AOT warmup (it usually
            # finished under the DMA) and hand its executables to the new
            # engine. Signature-checked against the BUILT engine — the
            # warmup resolved its config through the same _resolve_model,
            # but an executable compiled for the wrong eos/shape must
            # never install silently.
            from .exec_pool import exec_signature, mesh_shape

            t_transfer1 = time.monotonic()
            if warmup.signature == exec_signature(
                engine.cfg, mesh_shape(engine.mesh)
            ):
                warmup.install(engine, timeout=600)
            else:
                warmup.abort()
                warmup.wait(5)
                warmup.stats["errors"].append(
                    "signature mismatch with built engine; not installed"
                )
            build_stats["warmup"] = warmup.overlap_stats(
                window_t1=t_transfer1
            )
            self._last_warmup = warmup
        self._last_build_stats = build_stats
        sleeper = attach_sleep(
            engine,
            bucket_bytes=self._swap_bucket_bytes,
            quant_mode=self._sleep_quant,
            quant_hot_head=self._sleep_quant_hot_head,
            on_transfer=self.costs.observe_transfer,
        )
        if self._sleep_quant != "off" and not self.is_gang:
            # move the quantize/dequantize op compiles off the first
            # actuation's transfer window (and out of the cost oracle's
            # first bandwidth measurements) — the build already pays
            # compile time, this rides with it
            try:
                sleeper.warm_quant_ops()
            except Exception:  # noqa: BLE001 — warmup is best-effort
                logger.warning(
                    "transfer-quant op warmup failed", exc_info=True
                )
        # zero-drain pricing contract (engine/sleep.py peek_state): the
        # oracle's offload peeks exclude the KV pool exactly when an
        # actual offload of this engine will park first
        engine.zero_drain_park = self._zero_drain_parks()
        self.builds_total += 1
        return _ModelRuntime(
            model_id=model_id,
            engine=engine,
            sleeper=sleeper,
            tokenizer=tokenizer,
            hf_dir=hf_dir,
            checkpoint_dir=checkpoint_dir,
            # mesh builds carry shard-qualified digests (idempotent for
            # tier-staged maps that already are): sharded weight
            # identity = content + mesh shape + per-leaf spec
            digests=(
                self._qualify_digests(digests, model_cfg)
                if self._content_hash
                else None
            ),
        )

    def _install_runtime(self, rt: _ModelRuntime) -> None:
        """Point the service at a runtime (initial build or swap). The
        bundle is kept whole in `_runtime` (what a swap-out pools); the
        flat attributes mirror it for the many existing access sites, and
        `args.model` is the single source of the current model name —
        metrics labels, /v1/models, and launcher status all follow it."""
        self._runtime = rt
        self.engine = rt.engine
        self.sleeper = rt.sleeper
        self.tokenizer = rt.tokenizer
        self.hf_dir = rt.hf_dir
        self.checkpoint_dir = rt.checkpoint_dir
        self.args.model = rt.model_id

    def _current_runtime(self) -> _ModelRuntime:
        return self._runtime

    def _retire_model_series(self, previous: str) -> None:
        """Drop the outgoing model's per-model GAUGE label series on swap.
        These gauges are only ever written for the resident model, so
        after a swap the old series would report its last pre-swap value
        forever (a swapped-out model showing phantom queue depth /
        occupancy to the HPA and the fleet rollup). Histograms and
        counters are cumulative and stay. The arrival EWMA restarts too:
        its observations belonged to the outgoing model.

        With co-resident variants the live set is ``{args.model} ∪
        residents`` — not a single model — so retiring checks membership
        first: detaching one variant must never drop a series another
        live variant (or the base) is still writing, and a swap back to
        a model that happens to also be attached as a variant keeps its
        series too."""
        if (
            previous == self.args.model
            or previous == self._base_resident_id()
            or previous in self._residents
        ):
            return
        for g in (
            ENGINE_QUEUE_DEPTH,
            ENGINE_SLOT_OCCUPANCY,
            ENGINE_KV_USAGE,
            ENGINE_PREFIX_HIT_TOKENS,
            ENGINE_SPEC_PROPOSED,
            ENGINE_SPEC_ACCEPTED,
            ENGINE_ARRIVAL_RATE,
        ):
            try:
                g.remove(previous)
            except KeyError:
                pass
        with self._slo_mu:
            self._arrival = _RateEWMA(self._arrival.tau_s)

    # -- zero-drain actuation: preempt / park / resume (engine/parked.py;
    # docs/perf.md "Zero-drain actuation") -----------------------------------

    def _zero_drain_parks(self) -> bool:
        """True when an actuation on the CURRENT engine preempts-and-
        parks instead of aborting: --zero-drain on, single-process (gang
        bundles would be per-process partial state), and no device
        release (the release path's numpy staging restores full state —
        today's stall-and-resume semantics already hold there)."""
        return (
            self._zero_drain
            and not self.is_gang
            and not getattr(self, "release_on_sleep", False)
        )

    def _park_pageout_bytes(self) -> int:
        """Wire bytes a park of the current engine would page out d2h
        right now — per-page bytes (one pool-layout definition:
        PagePool.page_nbytes) times the live page count
        (engine.parked_page_ids), the SAME arithmetic the park itself
        performs, so predicted and actual park bytes agree exactly."""
        if not self._zero_drain_parks():
            return 0
        from .kv_cache import PagePool

        eng = self.engine
        m = eng.cfg.model
        per_page = PagePool.page_nbytes(
            m.num_layers,
            eng.cfg.page_size,
            m.num_kv_heads,
            m.head_dim,
            dtype=m.dtype,
        )
        return per_page * len(eng.parked_page_ids())

    def _park_current(self, park_pending: bool) -> Optional[Any]:
        """Preempt the current engine's live work into a ParkedRequests
        bundle: quiesce at the step boundary (caller holds the step
        lock), page the live KV out (fault point ``kvsave.d2h``), detach
        the scheduler, and move the displaced futures (and, on swap, the
        pre-engine pending queue) into the bundle. Returns None — engine
        untouched, caller falls back to the abort path — when the
        page-out failed."""
        eng = self.engine
        t0 = time.monotonic()
        try:
            bundle, finished = eng.park_requests(
                bucket_bytes=self._swap_bucket_bytes
            )
        except Exception:  # noqa: BLE001 — fall back to the abort path
            logger.warning(
                "zero-drain park failed; falling back to the abort path",
                exc_info=True,
            )
            return None
        t1 = time.monotonic()
        # requests a pipelined drain completed during the quiesce: they
        # finished on their own terms and were never preempted
        for req in finished:
            req.done_time = time.monotonic()
            self._observe_finished(req)
            fut = self._futures.pop(req.seq_id, None)
            if fut is not None:
                self._fut_seq.pop(id(fut), None)
                if not fut.done():
                    fut.set_result(req)
        for r in [pr.req for pr in bundle.live] + list(bundle.waiting):
            fut = self._futures.pop(r.seq_id, None)
            if fut is not None:
                self._fut_seq.pop(id(fut), None)
                bundle.futures[r.seq_id] = fut
            # the preempt/park/resume leg accounting: the whole parked
            # window [t0, resume-end] accumulates into preempt_s at
            # resume (or export time, for migrated bundles)
            r._park_t0 = t0
            r._park_t1 = t1
            r._park_pre_token = r.first_token_time is None
            if r.trace is not None:
                r.trace.add(
                    "request.preempt", t0, t1,
                    kv_bytes=bundle.kv_nbytes,
                )
        if park_pending:
            # still-queued HTTP submissions target the outgoing model
            # (validated against its vocab): they park too and re-enter
            # the pending queue on swap-back. pop-one-at-a-time, like
            # the abort path: submit() appends lock-free
            while self._pending:
                bundle.pending.append(self._pending.pop(0))
        if bundle.kv_nbytes:
            ENGINE_KV_PAGEOUT.labels(dir="d2h").inc(bundle.kv_nbytes)
            # the PURE gather window (engine.park_requests stamps it
            # around the d2h alone): quiesce/bookkeeping must not
            # anchor the bandwidth EWMA low
            self.costs.observe_transfer(
                "kvsave.d2h", bundle.kv_nbytes, bundle.pageout_s
            )
        with self._slo_mu:
            self._zd_preempted += bundle.preempted
            self._zd_parked_bytes += bundle.kv_nbytes
        return bundle

    def _abort_parked_bundle(
        self, bundle: Any, model: str, why: str
    ) -> int:
        """A parked bundle can never resume (KV restore failed, parked
        model evicted, L2 escalation dropped the host state): fail every
        displaced future with the existing ``state_loss`` cause — a
        clean abort, never a wedged slot."""
        exc = RuntimeError(why)
        n = 0
        now = time.monotonic()
        for r in [pr.req for pr in bundle.live] + list(bundle.waiting):
            fut = bundle.futures.get(r.seq_id)
            if fut is not None and not fut.done():
                fut.set_exception(exc)
            self._finish_request_trace(
                r, now, aborted=True, outcome="state_loss"
            )
            n += 1
        for entry in bundle.pending:
            fut = entry[3]
            if fut is not None and not fut.done():
                fut.set_exception(exc)
            tr = entry[16]
            if tr is not None:
                tr.finish(
                    entry[14], now, keep=True, outcome="state_loss",
                )
            n += 1
        if n:
            self._count_abort("state_loss", n)
            ENGINE_PREEMPTED.labels(model=model, outcome="aborted").inc(n)
        with self._slo_mu:
            self._zd_aborted += n
            self._zd_parked_bytes -= bundle.kv_nbytes
        return n

    def _resume_parked(self, rt: "_ModelRuntime") -> tuple:
        """Re-seat a runtime's parked bundle into its (awake) engine:
        page the KV back in (fault point ``kvrestore.h2d``), restore
        futures and pending submissions, and let the serving loop
        continue the streams mid-decode. Returns ``(resumed,
        pagein_bytes, seconds, dropped, shortfall)`` — ``dropped``
        counts parked requests whose clients vanished while parked;
        ``shortfall`` is True whenever the page-in moved fewer bytes
        than the bundle predicted (dropped clients, or a failed
        restore), so callers record the actuation UNPRICED instead of
        scoring a false byte-exactness miss. A restore failure is
        rolled back to a clean abort (cause ``state_loss``) with the
        engine healthy and serving — the transactional contract's abort
        leg; re-queued waiting/pending requests (which carried no KV and
        lost nothing) still count ``resumed``, so the documented
        preempted = resumed + aborted balance always closes."""
        from .parked import ParkedResumeFailed

        bundle = rt.parked
        if bundle is None:
            return 0, 0, 0.0, 0, False
        rt.parked = None
        with self._slo_mu:
            self._zd_parked_bytes -= bundle.kv_nbytes
        eng = rt.engine

        def _fut_dead(seq_id: int) -> bool:
            fut = bundle.futures.get(seq_id)
            return fut is not None and fut.done()

        # clients that went away while parked (their futures were
        # cancelled through the abort queue): drop before seating —
        # decoding for a dead client is pure waste
        dead = [pr for pr in bundle.live if _fut_dead(pr.req.seq_id)]
        bundle.live = [
            pr for pr in bundle.live if not _fut_dead(pr.req.seq_id)
        ]
        dead_wait = [r for r in bundle.waiting if _fut_dead(r.seq_id)]
        bundle.waiting = [
            r for r in bundle.waiting if not _fut_dead(r.seq_id)
        ]
        dropped = len(dead) + len(dead_wait)
        if dropped:
            self._count_abort("client", dropped)
            ENGINE_PREEMPTED.labels(
                model=rt.model_id, outcome="aborted"
            ).inc(dropped)
            with self._slo_mu:
                self._zd_aborted += dropped
            now = time.monotonic()
            for r in [pr.req for pr in dead] + dead_wait:
                # tail-keep: a stream the client dropped mid-park is a
                # lifecycle worth reading
                self._finish_request_trace(
                    r, now, aborted=True, outcome="aborted"
                )
        t0 = time.monotonic()
        try:
            n_live, moved = eng.resume_parked(
                bundle, bucket_bytes=self._swap_bucket_bytes
            )
        except ParkedResumeFailed as e:
            # rolled back inside the engine: no slot seated, pages
            # freed, waiting re-queued (they carried no KV). The live
            # requests' KV is gone — abort them cleanly, stay serving.
            exc = RuntimeError(
                f"preempted request aborted: zero-drain KV restore "
                f"failed ({e})"
            )
            nlost = 0
            tloss = time.monotonic()
            for pr in bundle.live:
                fut = bundle.futures.get(pr.req.seq_id)
                if fut is not None and not fut.done():
                    fut.set_exception(exc)
                self._finish_request_trace(
                    pr.req, tloss, aborted=True, outcome="state_loss"
                )
                nlost += 1
            for r in bundle.waiting:
                fut = bundle.futures.get(r.seq_id)
                if fut is not None and not fut.done():
                    self._futures[r.seq_id] = fut
                    self._fut_seq[id(fut)] = r.seq_id
            self._pending.extend(bundle.pending)
            if nlost:
                self._count_abort("state_loss", nlost)
                ENGINE_PREEMPTED.labels(
                    model=rt.model_id, outcome="aborted"
                ).inc(nlost)
            # the re-queued waiting/pending requests carried no KV and
            # continue serving: they RESUMED — without this the
            # documented preempted = resumed + aborted balance
            # (docs/operations.md) would never close after a drill
            requeued = len(bundle.waiting) + len(bundle.pending)
            if requeued:
                ENGINE_PREEMPTED.labels(
                    model=rt.model_id, outcome="resumed"
                ).inc(requeued)
            with self._slo_mu:
                self._zd_aborted += nlost
                self._zd_resumed += requeued
            ENGINE_RECOVERIES.labels(
                path="kvrestore", outcome="rolled_back"
            ).inc()
            self.degraded = (
                f"zero-drain resume aborted {nlost} preempted "
                f"request(s) with state_loss: {e}"
            )
            logger.warning(
                "zero-drain resume failed for %s; %d preempted "
                "request(s) aborted (state_loss)",
                rt.model_id, nlost, exc_info=True,
            )
            self._new_work.set()
            # shortfall=True: the prediction counted the bundle's pages,
            # none moved — the caller must record unpriced
            return 0, 0, time.monotonic() - t0, dropped, True
        t3 = time.monotonic()
        resume_s = t3 - t0
        if moved:
            ENGINE_KV_PAGEOUT.labels(dir="h2d").inc(moved)
            self.costs.observe_transfer("kvrestore.h2d", moved, resume_s)
        for r in [pr.req for pr in bundle.live] + list(bundle.waiting):
            # close the preempt window: parked dwell + the resume
            # transfer accumulate into the request's preempt leg
            pt0 = getattr(r, "_park_t0", None)
            if pt0 is not None:
                r.preempt_s += max(0.0, t3 - pt0)
                if getattr(r, "_park_pre_token", False):
                    r.preempt_pre_token_s += max(0.0, t3 - pt0)
                if r.trace is not None:
                    pt1 = getattr(r, "_park_t1", pt0)
                    r.trace.add("request.park", pt1, t0)
                    r.trace.add(
                        "request.resume", t0, t3, kv_bytes=moved
                    )
                r._park_t0 = None
        for seq_id, fut in bundle.futures.items():
            if not fut.done():
                self._futures[seq_id] = fut
                self._fut_seq[id(fut)] = seq_id
        self._pending.extend(bundle.pending)
        resumed = n_live + len(bundle.waiting) + len(bundle.pending)
        if resumed:
            ENGINE_PREEMPTED.labels(
                model=rt.model_id, outcome="resumed"
            ).inc(resumed)
        with self._slo_mu:
            self._zd_resumed += resumed
        self._new_work.set()
        return resumed, moved, resume_s, dropped, dropped > 0

    def _unpark_current(self, rt: "_ModelRuntime") -> None:
        """Rollback leg of a failed actuation that had already parked:
        put the preempted requests back into live serving (the
        transactional contract's restore leg). The engine's pool is
        rebuilt first when the park's detach is still in effect (a
        pre-transfer rejection); a swap_states rollback already rebuilt
        it through set_state."""
        if rt.parked is None:
            return
        try:
            if rt.engine.kv_detached:
                rt.engine.rebuild_kv_pool()
            self._resume_parked(rt)
        except Exception:  # noqa: BLE001 — _resume_parked aborts cleanly itself
            logger.warning(
                "zero-drain unpark after a failed actuation could not "
                "restore live serving", exc_info=True,
            )

    # -- live request migration: transactional parked-bundle handoff
    # between sibling instances (docs/operations.md "Draining a node
    # without dropping streams") ---------------------------------------------
    #
    # Verb sequence (the launcher drives it):
    #   source GET  /v1/parked/{model}   export_parked  — park + serialize
    #   dest   POST /v1/parked           import_parked  — verify + seat
    #   source POST /v1/parked/release   release_parked — commit + proxy
    #   source POST /v1/parked/abort     abort_migration — local resume
    # The export mints a single-use fence token; the import stores its ack
    # under it (a lost-ack retry replays the SAME ack instead of seating a
    # second copy), and release/abort spend it exactly once — a
    # double-resume is a 409 (MigrationRejected), never a duplicate stream.
    # Client streams only ever resolve through the SOURCE's original
    # futures: after release, per-stream watcher threads proxy the
    # destination's claim views back into them.

    def _migration_identity(self) -> Dict[str, Any]:
        """The model-identity block both ends of a handoff compare:
        name@checkpoint plus an order-independent fingerprint over the
        weight content digests. A runtime with neither digests nor a
        checkpoint directory (random-init dev weights) has no provable
        identity and is refused — KV seated onto different weights
        decodes garbage from valid-looking pages."""
        from . import parked as parked_mod

        rt = self._runtime
        digests = rt.digests if self._content_hash else None
        if not digests and not (rt.checkpoint_dir or ""):
            raise MigrationRejected(
                "no provable weight identity (no content digests and no "
                "checkpoint): migration between random-init engines is "
                "refused"
            )
        return {
            "model": self.args.model,
            "checkpoint_dir": rt.checkpoint_dir or "",
            "weight_fingerprint": (
                parked_mod.weight_fingerprint(digests) if digests else ""
            ),
            "page_size": int(self.args.page_size),
            "vocab_size": int(self.engine.cfg.model.vocab_size),
            "max_model_len": int(self.args.max_model_len or 0),
        }

    def _check_identity(self, theirs: Dict[str, Any]) -> None:
        """Import-side identity gate. Fingerprints are authoritative when
        both sides have them; otherwise the checkpoint path must match
        exactly (same shared filesystem) or the import is refused."""
        mine = self._migration_identity()
        if theirs.get("model") != mine["model"]:
            raise MigrationRejected(
                f"model identity mismatch: bundle is "
                f"{theirs.get('model')!r}, serving {mine['model']!r}"
            )
        fp_t = theirs.get("weight_fingerprint") or ""
        fp_m = mine["weight_fingerprint"]
        if fp_t and fp_m:
            if fp_t != fp_m:
                raise MigrationRejected(
                    "weight fingerprint mismatch: same model name, "
                    "different weights (refusing to seat KV onto foreign "
                    "weights)"
                )
        elif (
            not mine["checkpoint_dir"]
            or (theirs.get("checkpoint_dir") or "") != mine["checkpoint_dir"]
        ):
            raise MigrationRejected(
                "no comparable weight identity (enable --content-hash or "
                "serve both instances from the same checkpoint)"
            )
        if int(theirs.get("page_size", -1)) != mine["page_size"]:
            raise MigrationRejected(
                f"page_size mismatch ({theirs.get('page_size')} != "
                f"{mine['page_size']}): KV pages are not portable"
            )

    def _encode_pending(self, entry: tuple) -> Dict[str, Any]:
        """One parked ``_pending`` submit tuple as a wire spec. The
        future and streaming hook stay behind on the source (the proxy
        leg resolves them); ``submit_time`` is deliberately dropped —
        the importer stamps its own clock."""
        (prompt, max_tokens, temperature, _fut, _on_token, top_p,
         stop_seqs, presence, freq, want_alts, want_plp, seed,
         ignore_eos, logit_bias, _submit_t, variant, trace) = entry
        spec = {
            "prompt": [int(t) for t in prompt],
            "max_tokens": int(max_tokens),
            "temperature": float(temperature),
            "top_p": float(top_p),
            "stop_seqs": [list(s) for s in (stop_seqs or ())],
            "presence_penalty": float(presence),
            "frequency_penalty": float(freq),
            "want_top_logprobs": bool(want_alts),
            "want_prompt_logprobs": bool(want_plp),
            "seed": None if seed is None else int(seed),
            "ignore_eos": bool(ignore_eos),
            "logit_bias": {
                str(t): float(v) for t, v in (logit_bias or {}).items()
            },
            "variant": int(variant),
        }
        if trace is not None:
            ctx = trace.context()
            spec["trace"] = {
                "trace_id": ctx.trace_id, "span_id": ctx.span_id,
            }
        return spec

    def _decode_pending(self, spec: Dict[str, Any], fut: Any) -> tuple:
        """Rebuild a local ``_pending`` entry from a wire spec with a
        fresh destination-side future (the importer's claim record holds
        it; the source's original future is resolved by the proxy)."""
        tr = spec.get("trace")
        trace = None
        if (
            isinstance(tr, dict)
            and tr.get("trace_id")
            and tracing.enabled()
        ):
            # adopt the origin trace: destination spans join the SAME
            # trace_id, parented on the source's lifecycle root.
            # Migrated-in work is always retained (migration forensics).
            trace = tracing.RequestTrace(
                sampled=True,
                parent=tracing.SpanContext(
                    str(tr["trace_id"]), str(tr.get("span_id", ""))
                ),
            )
        return (
            [int(t) for t in spec["prompt"]],
            int(spec["max_tokens"]),
            float(spec["temperature"]),
            fut,
            None,
            float(spec["top_p"]),
            tuple(
                tuple(int(t) for t in s) for s in spec.get("stop_seqs", ())
            ),
            float(spec["presence_penalty"]),
            float(spec["frequency_penalty"]),
            bool(spec["want_top_logprobs"]),
            bool(spec["want_prompt_logprobs"]),
            None if spec["seed"] is None else int(spec["seed"]),
            bool(spec["ignore_eos"]),
            {int(t): float(v) for t, v in spec.get("logit_bias", {}).items()},
            time.monotonic(),
            int(spec.get("variant", 0)),
            trace,
        )

    def price_migrate(self) -> Dict[str, Any]:
        """Predicted cost of exporting this engine's live work to a
        sibling: live KV pages (the same arithmetic the park performs)
        plus the per-live-request scheduler rows, priced through the
        ``migrate.export`` bandwidth EWMA. What /v1/costs exposes so the
        launcher can pick cheap drain moments."""
        eng = self.engine
        park = self._park_pageout_bytes()
        live = sum(
            1 for r in eng._slots
            if r is not None and not r.done and not r.prefilling
        )
        # counts_row is [vocab] int32, key_data [2] uint32 — exact by
        # construction, like the KV figure (park_requests stamps
        # bundle.nbytes from the same quantities)
        meta = live * (int(eng.cfg.model.vocab_size) * 4 + 8)
        predicted = park + meta
        s, measured = self.costs.bandwidths.seconds_for(
            "migrate.export", predicted
        )
        return {
            "kind": "migrate",
            "model": self.args.model,
            "enabled": self._zero_drain_parks(),
            "predicted_bytes": predicted,
            "predicted_kv_bytes": park,
            "predicted_s": round(s, 6),
            "measured": measured,
            "requests": (
                live + len(eng._waiting) + len(self._pending)
            ),
        }

    def export_parked(self, model: str) -> Dict[str, Any]:
        """GET /v1/parked/{model}: preempt-and-park every live stream
        and serialize the bundle for a sibling. On success the engine is
        ALREADY serving again (fresh pool) — new arrivals never wait on
        the handoff — and the bundle is retained under a fence token
        until release/abort. Fault point ``migrate.export`` fires after
        the park: its drilled recovery is a LOCAL resume (the bundle
        never left this process, so nothing can be lost)."""
        from . import parked as parked_mod

        if model != self.args.model:
            raise MigrationRejected(
                f"model {model!r} is not the serving base "
                f"(serving {self.args.model!r})"
            )
        if self._residents:
            raise MigrationRejected(
                "co-resident variants attached "
                f"({sorted(self._residents)}); detach them "
                "(DELETE /v1/residents) before migrating the base"
            )
        if self.sleeper.is_sleeping:
            raise MigrationRejected(
                "instance is sleeping; wake it before migrating"
            )
        if not self._zero_drain_parks():
            raise MigrationRejected(
                "zero-drain parking unavailable (--zero-drain off, gang "
                "serving, or --release-on-sleep): nothing can be parked "
                "for migration"
            )
        if self._migration is not None:
            raise MigrationRejected(
                "a migration is already in flight "
                f"(fence {self._migration['token']})"
            )
        identity = self._migration_identity()
        try:
            pred: Optional[Dict[str, Any]] = self.price_migrate()
        except Exception:  # noqa: BLE001 — pricing must never block the verb
            pred = None
        t0 = time.monotonic()
        with tracing.span("migrate.export", model=model) as sp:
            with self._admin_lock():
                bundle = self._park_current(park_pending=True)
                if bundle is None:
                    raise MigrationFailed(
                        "zero-drain park failed; nothing was displaced "
                        "(streams still live)"
                    )
                try:
                    faults.fire("migrate.export")
                    doc = parked_mod.encode_wire(
                        bundle, identity,
                        chunk_bytes=self._swap_bucket_bytes,
                    )
                    import jax
                    import numpy as np

                    eng = self.engine
                    for spec in doc["requests"]["waiting"]:
                        if spec.get("seed") is None:
                            # pin the exact initial key THIS engine's
                            # admission would derive from (seed, seq_id):
                            # both differ on the importer
                            k = jax.random.fold_in(
                                jax.random.key(eng._seed + 1),
                                int(spec["seq_id"]),
                            )
                            spec["rng_key_data"] = parked_mod.pack_array(
                                np.asarray(jax.random.key_data(k))
                            )
                    doc["requests"]["pending"] = [
                        self._encode_pending(e) for e in bundle.pending
                    ]
                except Exception as e:  # noqa: BLE001 — any export-leg failure resumes locally
                    rt = self._runtime
                    rt.parked = bundle
                    self._unpark_current(rt)
                    with self._slo_mu:
                        self._mig["resumed_local"] += 1
                    ENGINE_MIGRATIONS.labels(
                        role="source", outcome="resumed_local"
                    ).inc()
                    self._record_actuation(
                        "migrate", model, trigger="export", tier="wire",
                        pred=None, actual_bytes=0,
                        actual_s=time.monotonic() - t0,
                        outcome="resumed_local",
                        extra={"error": f"{type(e).__name__}: {e}"},
                    )
                    raise MigrationFailed(
                        f"export failed ({e}); streams resumed locally"
                    ) from e
                import uuid

                self._migration_gen += 1
                token = (
                    f"mig-{self._migration_gen}-{uuid.uuid4().hex[:12]}"
                )
                doc["fence"] = {
                    "token": token,
                    "gen": self._migration_gen,
                    "source_model": model,
                }
                self._migration = {
                    "token": token,
                    "bundle": bundle,
                    "model": model,
                    "pred": pred,
                    "t0": t0,
                    "nbytes": int(doc["nbytes"]),
                    "requests": bundle.preempted,
                }
                # the handoff spans separate HTTP round-trips: rebuild
                # the pool NOW so new arrivals serve during the window —
                # the abort leg's local resume re-seats into it, exactly
                # like _unpark_current after a failed swap
                self.engine.rebuild_kv_pool()
            encode_s = time.monotonic() - t0
            nbytes = int(doc["nbytes"])
            if nbytes:
                self.costs.observe_transfer(
                    "migrate.export", nbytes, encode_s
                )
            ENGINE_MIGRATE_BYTES.labels(dir="export").inc(nbytes)
            with self._slo_mu:
                self._mig["exported"] += 1
                self._mig["bytes_out"] += nbytes
            sp.set(
                nbytes=nbytes, requests=bundle.preempted, fence=token
            )
            self._new_work.set()
            return doc

    def import_parked(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        """POST /v1/parked: verify and seat a sibling's exported bundle.
        Everything is checked BEFORE any engine state moves (wire
        version, every KV chunk digest, weight identity, slot/page
        capacity) so a refusal leaves the destination untouched; a seat
        failure strips the foreign requests back out (clean rollback).
        The ack is stored under the fence token BEFORE the ``migrate.ack``
        fault point fires, so a lost-ack retry replays the SAME ack
        instead of seating a duplicate."""
        from . import parked as parked_mod
        from .engine import Request
        from .kv_cache import PageAllocator

        fence = doc.get("fence") or {}
        token = str(fence.get("token") or "")
        if not token:
            raise ValueError("parked import without a fence token")
        with self._slo_mu:
            replay = self._import_acks.get(token)
        if replay is not None:
            # idempotent lost-ack retry: the seat already happened
            return dict(replay)
        if token in self._spent_fences:
            raise MigrationRejected(
                f"fence token {token!r} already spent "
                "(double-resume refused)"
            )
        if self.sleeper.is_sleeping:
            raise MigrationRejected(
                "instance is sleeping; wake it before importing"
            )
        if self._residents:
            raise MigrationRejected(
                "co-resident variants attached "
                f"({sorted(self._residents)}); detach them "
                "(DELETE /v1/residents) before importing a parked bundle"
            )
        self._check_identity(doc.get("identity") or {})
        t0 = time.monotonic()
        with tracing.span(
            "migrate.import", model=self.args.model, fence=token
        ) as sp:
            # decode verifies every chunk digest (ValueError -> 400)
            bundle, pending_specs = parked_mod.decode_wire(doc, Request)
            try:
                faults.fire("migrate.import")
            except faults.FaultError as e:
                with self._slo_mu:
                    self._mig["rolled_back"] += 1
                ENGINE_MIGRATIONS.labels(
                    role="destination", outcome="rolled_back"
                ).inc()
                raise MigrationFailed(
                    f"import failed before seating ({e}); destination "
                    "clean"
                ) from e
            import uuid

            with self._admin_lock():
                eng = self.engine
                if eng.kv_detached:
                    raise MigrationRejected(
                        "KV pool detached (mid-actuation); retry after "
                        "it settles"
                    )
                free_slots = sum(1 for s in eng._slots if s is None)
                if len(bundle.live) > free_slots:
                    raise MigrationRejected(
                        f"no capacity: {len(bundle.live)} live streams "
                        f"need slots, {free_slots} free"
                    )
                # conservative (sharing-blind) page bound: resume
                # allocates each live request's FULL budget
                need_pages = sum(
                    PageAllocator.pages_needed(
                        len(pr.req.prompt) + pr.req.max_new_tokens,
                        self.args.page_size,
                    )
                    for pr in bundle.live
                )
                if need_pages > eng.allocator.available:
                    raise MigrationRejected(
                        f"no capacity: bundle needs up to {need_pages} "
                        f"KV pages, {eng.allocator.available} free"
                    )
                # re-key into this engine's id space; the ack's claims
                # map (source seq_id -> claim id) lets the source proxy
                # each stream back to its original client
                claims: Dict[str, str] = {}
                recs: List[tuple] = []
                for pr in bundle.live:
                    old = int(pr.req.seq_id)
                    pr.req.seq_id = eng.new_seq_id()
                    cid = uuid.uuid4().hex
                    claims[str(old)] = cid
                    recs.append((cid, pr.req))
                for r in bundle.waiting:
                    old = int(r.seq_id)
                    r.seq_id = eng.new_seq_id()
                    cid = uuid.uuid4().hex
                    claims[str(old)] = cid
                    recs.append((cid, r))
                waiting_snapshot = list(bundle.waiting)
                try:
                    n_live, moved = eng.resume_parked(
                        bundle, bucket_bytes=self._swap_bucket_bytes
                    )
                except parked_mod.ParkedResumeFailed as e:
                    # the engine re-queued bundle.waiting — right for a
                    # LOCAL resume, wrong here: these are foreign
                    # requests the source still owns. Strip them so the
                    # rollback really is clean.
                    drop = {id(r) for r in waiting_snapshot}
                    eng._waiting = [
                        r for r in eng._waiting if id(r) not in drop
                    ]
                    with self._slo_mu:
                        self._mig["rolled_back"] += 1
                    ENGINE_MIGRATIONS.labels(
                        role="destination", outcome="rolled_back"
                    ).inc()
                    raise MigrationFailed(
                        f"import seat failed ({e}); destination rolled "
                        "back clean"
                    ) from e
                t_seat = time.monotonic()
                for cid, r in recs:
                    fut: concurrent.futures.Future = (
                        concurrent.futures.Future()
                    )
                    self._futures[r.seq_id] = fut
                    self._fut_seq[id(fut)] = r.seq_id
                    self._imported_claims[cid] = {"req": r, "fut": fut}
                    if r.trace_parent and tracing.enabled():
                        # join the origin trace: same trace_id, spans
                        # parented on the source's lifecycle root.
                        # Always retained — the bench's shared-trace_id
                        # acceptance reads both sides' /v1/traces.
                        r.trace = tracing.RequestTrace(
                            sampled=True,
                            parent=tracing.SpanContext(
                                str(r.trace_parent["trace_id"]),
                                str(r.trace_parent.get("span_id", "")),
                            ),
                        )
                        r.trace.add(
                            "request.resume", t0, t_seat,
                            migrated=True, fence=token,
                        )
                for i, spec in enumerate(pending_specs):
                    fut = concurrent.futures.Future()
                    cid = uuid.uuid4().hex
                    claims[f"p{i}"] = cid
                    self._imported_claims[cid] = {"req": None, "fut": fut}
                    self._pending.append(self._decode_pending(spec, fut))
            if moved:
                # kvrestore.h2d's bandwidth EWMA deliberately NOT
                # observed here: this window includes decode+verify, and
                # that EWMA only ever sees pure transfer windows
                ENGINE_KV_PAGEOUT.labels(dir="h2d").inc(moved)
            import_s = time.monotonic() - t0
            nbytes = int(doc.get("nbytes", 0))
            if nbytes:
                self.costs.observe_transfer(
                    "migrate.import", nbytes, import_s
                )
            ENGINE_MIGRATE_BYTES.labels(dir="import").inc(nbytes)
            n_req = len(recs) + len(pending_specs)
            with self._slo_mu:
                self._mig["imported"] += 1
                self._mig["bytes_in"] += nbytes
                self._mig["requests_in"] += n_req
            ENGINE_MIGRATIONS.labels(
                role="destination", outcome="imported"
            ).inc()
            self._record_actuation(
                "migrate", self.args.model, trigger="import",
                tier="wire", pred=None, actual_bytes=nbytes,
                actual_s=import_s, outcome="imported",
                extra={"requests": n_req, "fence": token},
            )
            ack = {
                "ok": True,
                "fence_token": token,
                "model": self.args.model,
                "seated": n_live,
                "waiting": len(waiting_snapshot),
                "pending": len(pending_specs),
                "requests": n_req,
                "kv_bytes": moved,
                "claims": claims,
            }
            with self._slo_mu:
                self._import_acks[token] = dict(ack)
            self._new_work.set()
            sp.set(nbytes=nbytes, requests=n_req, seated=n_live)
            try:
                faults.fire("migrate.ack")
            except faults.FaultError as e:
                # the seat SUCCEEDED and the stored ack replays on the
                # retry — only the response is lost (the drilled
                # lost-ack leg)
                raise MigrationFailed(
                    f"import ack lost ({e}); retry the import (fenced, "
                    "idempotent)"
                ) from e
            return ack

    def release_parked(
        self,
        token: str,
        dest: str = "",
        claims: Optional[Dict[str, str]] = None,
    ) -> Dict[str, Any]:
        """POST /v1/parked/release: the destination acked the import —
        commit. Spends the fence (a second release, or an abort after
        this, is a 409) and hands every displaced stream to a watcher
        thread that proxies the destination's claim back into the
        ORIGINAL future and streaming hook: the client never reconnects,
        and exactly-once holds because only the source resolves these
        futures."""
        mig = self._migration
        if mig is None or mig["token"] != token:
            raise MigrationRejected(
                f"fence token {token!r} is not the in-flight migration "
                "(spent or unknown)"
            )
        self._migration = None
        self._spent_fences.add(token)
        bundle = mig["bundle"]
        claims = dict(claims or {})
        model = mig["model"]
        with tracing.span("migrate.release", model=model, fence=token):
            watchers = 0
            lost = 0
            gone = 0
            gone_claims: List[str] = []
            now = time.monotonic()
            for r in [pr.req for pr in bundle.live] + list(bundle.waiting):
                fut = bundle.futures.get(r.seq_id)
                cid = claims.get(str(int(r.seq_id)))
                if fut is None or fut.done():
                    # client dropped while the bundle was in flight: ONE
                    # abort (reason=client) HERE, and the destination is
                    # told to abort its claim so it both stops decoding
                    # and counts its own single client abort
                    gone += 1
                    if cid:
                        gone_claims.append(cid)
                    self._finish_migrate_trace(
                        r, mig["t0"], now, dest, outcome="aborted"
                    )
                    continue
                if not cid:
                    fut.set_exception(RuntimeError(
                        "migrated stream lost: destination acked no "
                        "claim for it"
                    ))
                    self._count_abort("state_loss")
                    lost += 1
                    self._finish_migrate_trace(
                        r, mig["t0"], now, dest, outcome="state_loss"
                    )
                    continue
                self._finish_migrate_trace(
                    r, mig["t0"], now, dest, outcome="migrated"
                )
                self._start_claim_watcher(dest, cid, r, fut)
                watchers += 1
            for i, entry in enumerate(bundle.pending):
                fut = entry[3]
                cid = claims.get(f"p{i}")
                tr = entry[16]
                if fut is None or fut.done():
                    gone += 1
                    if cid:
                        gone_claims.append(cid)
                    if tr is not None:
                        tr.add(
                            "request.migrate", mig["t0"], now,
                            dest=dest or "", outcome="aborted",
                        )
                        tr.finish(
                            entry[14], now, keep=True, outcome="aborted"
                        )
                    continue
                if not cid:
                    fut.set_exception(RuntimeError(
                        "migrated submission lost: destination acked no "
                        "claim for it"
                    ))
                    self._count_abort("state_loss")
                    lost += 1
                    if tr is not None:
                        tr.add(
                            "request.migrate", mig["t0"], now,
                            dest=dest or "", outcome="state_loss",
                        )
                        tr.finish(
                            entry[14], now, keep=True,
                            outcome="state_loss",
                        )
                    continue
                if tr is not None:
                    tr.add(
                        "request.migrate", mig["t0"], now,
                        dest=dest or "", outcome="migrated",
                    )
                    tr.finish(
                        entry[14], now, keep=True, outcome="migrated"
                    )
                self._start_claim_watcher(
                    dest, cid, self._pending_proxy_req(entry), fut
                )
                watchers += 1
            n = bundle.preempted
            migrated = n - lost - gone
            if gone:
                # the dropped-client invariant (tests pin it): exactly
                # one reason=client abort and one outcome=aborted on the
                # source for a migrated-then-disconnected stream
                self._count_abort("client", gone)
            if lost or gone:
                ENGINE_PREEMPTED.labels(
                    model=model, outcome="aborted"
                ).inc(lost + gone)
            if migrated:
                ENGINE_PREEMPTED.labels(
                    model=model, outcome="migrated"
                ).inc(migrated)
            with self._slo_mu:
                self._zd_migrated += migrated
                self._zd_aborted += lost + gone
                self._zd_parked_bytes -= bundle.kv_nbytes
                self._mig["committed"] += 1
                self._mig["requests_out"] += migrated
            if gone_claims:
                self._abort_claims_async(dest, gone_claims)
            ENGINE_MIGRATIONS.labels(
                role="source", outcome="committed"
            ).inc()
            self._record_actuation(
                "migrate", model, trigger="migrate", tier="wire",
                pred=mig["pred"], actual_bytes=mig["nbytes"],
                actual_s=time.monotonic() - mig["t0"],
                outcome="committed",
                extra={
                    "requests": n,
                    "proxied": watchers,
                    "fence": token,
                    "dest": dest or None,
                },
            )
            return {
                "ok": True,
                "fence_token": token,
                "model": model,
                "migrated": migrated,
                "proxied": watchers,
            }

    def abort_migration(self, token: str) -> Dict[str, Any]:
        """POST /v1/parked/abort: the handoff failed after export (the
        import errored twice, or the destination is gone) — spend the
        fence and resume the bundle LOCALLY, the drilled recovery for
        every single-fault case. Only an explicit double fault (the
        local KV page-in failing too) degrades to the existing
        ``state_loss`` abort."""
        mig = self._migration
        if mig is None or mig["token"] != token:
            raise MigrationRejected(
                f"fence token {token!r} is not the in-flight migration "
                "(spent or unknown)"
            )
        self._migration = None
        self._spent_fences.add(token)
        bundle = mig["bundle"]
        model = mig["model"]
        resumed, moved, seconds, dropped = 0, 0, 0.0, 0
        shortfall = True
        with tracing.span("migrate.abort", model=model, fence=token):
            rt = self._runtime
            with self._admin_lock():
                rt.parked = bundle
                try:
                    if rt.engine.kv_detached:
                        rt.engine.rebuild_kv_pool()
                except Exception:  # noqa: BLE001 — double fault: abort below
                    logger.warning(
                        "KV pool rebuild failed while aborting a "
                        "migration", exc_info=True,
                    )
                if rt.parked is not None and not rt.engine.kv_detached:
                    resumed, moved, seconds, dropped, shortfall = (
                        self._resume_parked(rt)
                    )
                if rt.parked is not None:
                    b, rt.parked = rt.parked, None
                    self._abort_parked_bundle(
                        b, model,
                        "preempted request aborted: migration aborted "
                        "and the KV pool could not be rebuilt "
                        "(state_loss)",
                    )
            # _resume_parked's failure leg returns resumed=0 with
            # shortfall set; a live-carrying bundle that hit it lost KV
            outcome = "resumed_local"
            if shortfall and resumed == 0 and mig["requests"] > dropped:
                outcome = "state_loss"
            with self._slo_mu:
                self._mig[outcome] += 1
            ENGINE_MIGRATIONS.labels(role="source", outcome=outcome).inc()
            self._record_actuation(
                "migrate", model, trigger="abort", tier="wire",
                pred=None, actual_bytes=moved, actual_s=seconds,
                outcome=outcome,
                extra={
                    "resumed": resumed,
                    "dropped": dropped,
                    "fence": token,
                },
            )
            return {
                "ok": outcome == "resumed_local",
                "outcome": outcome,
                "fence_token": token,
                "model": model,
                "resumed": resumed,
            }

    def claim_view(
        self, claim_id: str, wait_s: float = 0.0, have: int = -1
    ) -> Dict[str, Any]:
        """GET /v1/parked/claims/{id}: the destination's view of one
        migrated-in stream. Long-poll flavored: blocks up to ``wait_s``
        until the stream finishes or more than ``have`` holdback-safe
        tokens exist. Mid-flight snapshots exclude tokens a stop
        sequence might yet strip (engine._stream's exact rule), so the
        source proxy never streams content the engine itself would have
        held back."""
        from .engine import _stop_holdback

        rec = self._imported_claims.get(claim_id)
        if rec is None:
            raise ValueError(f"unknown claim {claim_id!r}")
        deadline = time.monotonic() + max(0.0, min(float(wait_s), 30.0))
        while True:
            fut = rec["fut"]
            if fut.done():
                from . import parked as parked_mod

                try:
                    req = fut.result()
                except Exception as e:  # noqa: BLE001 — surfaced to the proxy
                    return {
                        "done": True,
                        "error": f"{type(e).__name__}: {e}",
                    }
                return {
                    "done": True,
                    "request": parked_mod.encode_request(req),
                    "finish_reason": req.finish_reason,
                }
            req = rec.get("req")
            if req is None:
                # a parked PENDING submission: the Request exists only
                # after the serving loop admits it
                seq = self._fut_seq.get(id(fut))
                if seq is not None:
                    req = self._find_live_request(seq)
                    if req is not None:
                        rec["req"] = req
            toks: List[int] = []
            if req is not None:
                out = list(req.out_tokens)
                hold = _stop_holdback(out, req.stop_seqs)
                toks = out[: len(out) - hold] if hold else out
            if len(toks) > have or time.monotonic() >= deadline:
                return {"done": False, "tokens": [int(t) for t in toks]}
            time.sleep(0.02)

    def abort_claim(self, claim_id: str) -> Dict[str, Any]:
        """DELETE /v1/parked/claims/{id}: the source's proxy learned its
        client went away — stop generating for the migrated-in stream
        here too. Funnels through the normal abort choke point so this
        instance records its own single client abort; the source records
        the matching one when it reaps the dropped future."""
        rec = self._imported_claims.pop(claim_id, None)
        if rec is None:
            raise ValueError(f"unknown claim {claim_id!r}")
        fut = rec["fut"]
        aborted = not fut.done()
        if aborted:
            self.abort(fut)
        return {"ok": True, "claim_id": claim_id, "aborted": aborted}

    def _find_live_request(self, seq_id: int):
        eng = self.engine
        for r in eng._slots:
            if r is not None and r.seq_id == seq_id:
                return r
        for r in eng._waiting:
            if r.seq_id == seq_id:
                return r
        return None

    def _pending_proxy_req(self, entry: tuple):
        """A host-side Request stand-in for a parked PENDING
        submission's proxy leg: the watcher streams into it and resolves
        the original future with it — field-compatible with what the
        local serving loop would have resolved."""
        from .engine import Request

        spec = self._encode_pending(entry)
        req = Request(
            seq_id=-1,
            prompt=[int(t) for t in spec["prompt"]],
            max_new_tokens=int(spec["max_tokens"]),
            temperature=float(spec["temperature"]),
        )
        req.top_p = float(spec["top_p"])
        req.stop_seqs = tuple(
            tuple(int(t) for t in s) for s in spec["stop_seqs"]
        )
        req.presence_penalty = float(spec["presence_penalty"])
        req.frequency_penalty = float(spec["frequency_penalty"])
        req.want_top_logprobs = bool(spec["want_top_logprobs"])
        req.want_prompt_logprobs = bool(spec["want_prompt_logprobs"])
        req.seed = spec["seed"]
        req.ignore_eos = bool(spec["ignore_eos"])
        req.logit_bias = {
            int(t): float(v) for t, v in spec["logit_bias"].items()
        }
        req.variant = int(spec["variant"])
        req.on_token = entry[4]
        req.submit_time = entry[14]
        return req

    def _claim_fetch(
        self, dest: str, claim_id: str, have: int, wait_s: float
    ) -> Dict[str, Any]:
        """Fetch one claim view from the destination engine. A seam:
        tests inject an in-process fetcher here; the default speaks the
        engine HTTP API."""
        import urllib.request

        url = (
            f"{dest.rstrip('/')}/v1/parked/claims/{claim_id}"
            f"?have={int(have)}&wait_s={wait_s:g}"
        )
        with urllib.request.urlopen(url, timeout=wait_s + 10.0) as resp:
            return json.loads(resp.read().decode())

    def _claim_abort(self, dest: str, claim_id: str) -> None:
        """Tell the destination a migrated stream's client went away
        (DELETE its claim). A seam like _claim_fetch: tests inject an
        in-process caller; the default speaks the engine HTTP API."""
        import urllib.request

        url = f"{dest.rstrip('/')}/v1/parked/claims/{claim_id}"
        urllib.request.urlopen(
            urllib.request.Request(url, method="DELETE"), timeout=10.0
        ).close()

    def _abort_claims_async(self, dest: str, claim_ids: List[str]) -> None:
        """Best-effort destination claim aborts off-thread (release and
        _drain_aborts run under locks; a dead destination must not wedge
        them). Failure is tolerable — the destination merely decodes a
        dead stream to completion and counts it finished."""
        if not dest or not claim_ids:
            return

        def run() -> None:
            for cid in claim_ids:
                try:
                    self._claim_abort(dest, cid)
                except Exception:  # noqa: BLE001 — best-effort
                    logger.debug(
                        "claim abort %s on %s failed", cid, dest,
                        exc_info=True,
                    )

        threading.Thread(
            target=run, name="migrate-claim-abort", daemon=True
        ).start()

    def _start_claim_watcher(
        self, dest: str, claim_id: str, req: Any, fut: Any
    ) -> None:
        # register BEFORE the thread starts: a client disconnect racing
        # the watcher must find the proxy record in _drain_aborts
        self._proxied[id(fut)] = {"dest": dest, "claim": claim_id}
        threading.Thread(
            target=self._watch_claim,
            args=(dest, claim_id, req, fut),
            name=f"migrate-claim-{claim_id[:8]}",
            daemon=True,
        ).start()

    def _proxy_stream(self, req: Any, done: bool) -> None:
        """Deliver proxied tokens through the original streaming hook
        with engine._stream's exact contract: ``req.done`` is True only
        on the final delivered token (the SSE writer keys its terminator
        on it). Claim snapshots are already holdback-safe."""
        if req.on_token is None:
            req.streamed = len(req.out_tokens)
            req.done = done
            return
        tail = req.out_tokens[req.streamed:]
        try:
            for i, t in enumerate(tail):
                req.done = done and i == len(tail) - 1
                req.on_token(req, t)
                req.streamed += 1
        finally:
            req.done = done

    def _watch_claim(
        self, dest: str, claim_id: str, req: Any, fut: Any
    ) -> None:
        """Source-side proxy for one migrated stream: poll the
        destination's claim, forward newly-safe tokens through the
        original ``on_token`` hook, and resolve the original future with
        the finished request. Destination-side aborts and a destination
        that stays unreachable surface as the existing ``state_loss``
        abort — never a silent hang."""
        try:
            self._watch_claim_inner(dest, claim_id, req, fut)
        finally:
            # idempotent: _drain_aborts may have popped it already (and
            # counted the client abort); this keeps the registry clean
            # on the watcher's own terminal paths
            self._proxied.pop(id(fut), None)

    def _watch_claim_inner(
        self, dest: str, claim_id: str, req: Any, fut: Any
    ) -> None:
        backoff = 0.1
        first_fail: Optional[float] = None
        while not self._stop:
            if fut.done():
                return  # client went away; nothing left to proxy
            try:
                view = self._claim_fetch(
                    dest, claim_id, len(req.out_tokens), 5.0
                )
            except Exception as e:  # noqa: BLE001 — network/dest failures retry
                now = time.monotonic()
                if first_fail is None:
                    first_fail = now
                if now - first_fail > 60.0:
                    if not fut.done():
                        fut.set_exception(RuntimeError(
                            "migrated stream lost: destination "
                            f"unreachable ({e})"
                        ))
                        self._count_abort("state_loss")
                    return
                time.sleep(backoff)
                backoff = min(2.0, backoff * 2)
                continue
            first_fail = None
            backoff = 0.1
            if view.get("done"):
                err = view.get("error")
                if err:
                    if not fut.done():
                        fut.set_exception(RuntimeError(
                            "migrated stream aborted on the "
                            f"destination: {err}"
                        ))
                        self._count_abort("state_loss")
                    return
                from . import parked as parked_mod

                final = parked_mod.decode_request(
                    view["request"], type(req)
                )
                req.out_tokens = final.out_tokens
                req.out_logprobs = final.out_logprobs
                req.out_top_logprobs = final.out_top_logprobs
                req.prompt_logprobs = final.prompt_logprobs
                req.pos = final.pos
                req.cached_tokens = final.cached_tokens
                req.stop_requested = final.stop_requested
                req.finish_reason = view.get("finish_reason", "")
                req.done_time = time.monotonic()
                self._proxy_stream(req, done=True)
                if not fut.done():
                    fut.set_result(req)
                return
            toks = view.get("tokens") or []
            if len(toks) > len(req.out_tokens):
                req.out_tokens = [int(t) for t in toks]
                self._proxy_stream(req, done=False)

    # -- actuation cost oracle (GET /v1/costs; docs/operations.md
    # "Pricing an actuation") ------------------------------------------------

    def _model_cfg_cheap(self, model_id: str):
        """Model config for `model_id` WITHOUT the tokenizer load
        ``_resolve_model`` pays: pricing every candidate in one
        /v1/costs call must stay cheap (config.json read for hf:,
        factory call for named configs)."""
        if model_id.startswith("hf:"):
            from ..models import hf as hf_models

            return hf_models.config_from_hf(
                model_id[3:], quantization=self.args.quantization or ""
            )
        if model_id not in MODEL_CONFIGS:
            raise ValueError(f"unknown model {model_id!r}")
        model_cfg = MODEL_CONFIGS[model_id]()
        if (
            self.args.quantization
            and model_cfg.quantization != self.args.quantization
        ):
            import dataclasses

            model_cfg = dataclasses.replace(
                model_cfg, quantization=self.args.quantization
            )
        return model_cfg

    def _kv_pool_nbytes(self, model_cfg) -> int:
        """Device bytes of the KV page pool a runtime for `model_cfg`
        creates — counted in a cold build's ``bytes_in``, so the
        oracle's cold predictions must count it identically (the layout
        lives in ONE place: PagePool.estimate_nbytes)."""
        from .kv_cache import PagePool

        return PagePool.estimate_nbytes(
            model_cfg.num_layers,
            self.args.num_pages,
            self.args.page_size,
            model_cfg.num_kv_heads,
            model_cfg.head_dim,
            dtype=model_cfg.dtype,
        )

    def _offload_wire_bytes(self) -> int:
        """Wire bytes a level-1 offload of the CURRENT runtime would
        move d2h — payload bytes for --sleep-quant-eligible leaves,
        priced from shapes alone (models/quant.payload_nbytes)."""
        import jax

        from ..models import quant as transfer_quant

        state = self.sleeper._peek_state()
        leaves = jax.tree.leaves(state)
        plan = self.sleeper._quant_plan(state)
        if not plan:
            return sum(x.nbytes for x in leaves)
        mode = self.sleeper.quant_mode
        return sum(
            transfer_quant.payload_nbytes(x.shape, mode) if f else x.nbytes
            for x, f in zip(leaves, plan)
        )

    def price_swap(
        self,
        model: str,
        checkpoint_dir: str = "",
        _offload_wire: Optional[int] = None,
        _exec_desc: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Pre-transfer pricing of a hot-swap to `model`: predicted wire
        bytes (exact-by-construction for pool-hit delta/quant swaps —
        the dry-run shares ``swap_states``'s planner; shape/manifest
        estimates for the cold tiers) and predicted seconds (bytes ÷
        the measured per-kind bandwidth EWMAs). Read-only and
        lock-free: nothing is consumed, nothing moves — concurrent
        actuations make the answer advisory, never wrong-state."""
        if model.startswith("hf:"):
            if not model[3:]:
                raise ValueError("swap model hf: needs a directory path")
        elif model not in MODEL_CONFIGS:
            raise ValueError(
                f"unknown model {model!r}; known: "
                f"{sorted(MODEL_CONFIGS)} or hf:<model-dir>"
            )
        book = self.costs.bandwidths
        out: Dict[str, Any] = {
            "kind": "swap",
            "model": model,
            "checkpoint_dir": checkpoint_dir,
        }
        if model == self.args.model and (
            not checkpoint_dir or checkpoint_dir == self.checkpoint_dir
        ):
            return {
                **out,
                "tier": "resident",
                "predicted_bytes": 0,
                "predicted_bytes_out": 0,
                "predicted_bytes_in": 0,
                "predicted_s": 0.0,
                "measured": True,
            }
        entry = (
            self.model_pool.peek(_pool_key(model, checkpoint_dir))
            if checkpoint_dir
            else self.model_pool.peek_match(model)
        )
        prefetched = entry is not None and isinstance(
            entry.runtime, _PrefetchedWeights
        )
        # costs_view prices many candidates in one call; the outgoing
        # runtime and exec pool are the same for all of them, so it
        # precomputes these once and passes them down
        exec_desc = (
            _exec_desc
            if _exec_desc is not None
            else self.exec_pool.describe()
        )
        compile_est = exec_desc.get("mean_compile_s", 0.0)
        if entry is not None and not prefetched:
            # pool-hit slept runtime: the EXACT planner swap_states will
            # run — byte prediction is deterministic from digests/shapes
            from .sleep import plan_swap

            p = plan_swap(
                self.sleeper,
                entry.runtime.sleeper,
                bucket_bytes=self._swap_bucket_bytes,
                out_digests=(
                    self._runtime.digests if self._content_hash else None
                ),
                in_digests=(
                    entry.runtime.digests if self._content_hash else None
                ),
                quant=self._sleep_quant,
            )
            # zero-drain parked-KV payload rides both directions: the
            # outgoing park's page-out and — when the candidate is a
            # previously-parked runtime — its bundle's page-in. Without
            # these the byte-exactness contract (byte_exact_frac)
            # silently breaks on the first preempting swap.
            park_out = self._park_pageout_bytes()
            pb = getattr(entry.runtime, "parked", None)
            park_in = pb.kv_nbytes if pb is not None else 0
            out_s, m1 = book.seconds_for(
                "swap.d2h", p["wire_out"] + park_out
            )
            in_s, m2 = book.seconds_for(
                "swap.h2d", p["wire_in"] + park_in
            )
            if book.has("swap.total"):
                # effective whole-verb bandwidth from prior pool-hit
                # swaps: predicts the wall directly (fixed per-swap
                # overhead included), which the per-window components
                # can't see
                predicted_s, m_tot = book.seconds_for(
                    "swap.total", p["bytes_moved"] + park_out + park_in
                )
                m1 = m2 = m_tot
            else:
                # one-bucket swaps run the two directions sequentially;
                # the double-buffered overlap needs >= 2 outgoing buckets
                predicted_s = (
                    max(out_s, in_s)
                    if p["buckets_out"] > 1
                    else out_s + in_s
                )
            return {
                **out,
                "tier": "pool",
                "predicted_bytes": p["bytes_moved"] + park_out + park_in,
                "predicted_bytes_out": p["wire_out"] + park_out,
                "predicted_bytes_in": p["wire_in"] + park_in,
                "predicted_kv_pageout_bytes": park_out,
                "predicted_kv_pagein_bytes": park_in,
                "predicted_bytes_deduped": p["bytes_deduped"],
                "predicted_deduped_leaves": p["deduped_leaves"],
                "predicted_bytes_full": p["bytes_full"],
                "quant": p["quant"],
                "predicted_s": round(predicted_s, 6),
                "predicted_d2h_s": round(out_s, 6),
                "predicted_h2d_s": round(in_s, 6),
                "measured": bool(m1 and m2),
                # a slept runtime keeps its compiled programs: no compile
                "compile_estimate_s": 0.0,
            }
        # Cold tiers: the outgoing leg is a level-1 offload of the
        # current runtime; the incoming leg streams a host tree (staged /
        # tier-rebuilt / checkpoint-read) and creates the KV pool — the
        # same figures a cold build's bytes_in reports.
        offload_wire = (
            _offload_wire
            if _offload_wire is not None
            else self._offload_wire_bytes()
        )
        # under zero-drain the offload peeks exclude the KV pool (the
        # park moves the live pages compactly instead): price the park's
        # page-out with the outgoing leg it rides
        park_out = self._park_pageout_bytes()
        d2h_s, m_out = book.seconds_for(
            "sleep.d2h", offload_wire + park_out
        )
        model_cfg = self._model_cfg_cheap(model)
        kv_bytes = self._kv_pool_nbytes(model_cfg)
        read_bytes = 0
        if prefetched:
            tier = "prefetched"
            stream_bytes = int(entry.nbytes)
            params_full = stream_bytes
            if entry.runtime.quant_metas is not None:
                # staged payloads stream compressed; the built engine
                # holds (and bytes_in reports) full-precision arrays
                from ..models import hf as hf_models

                params_full = hf_models.estimate_param_bytes(model_cfg)
        else:
            staged = None
            if self._content_hash:
                if checkpoint_dir:
                    got = self.model_pool.peek_staged(
                        _pool_key(model, checkpoint_dir)
                    )
                    staged = (
                        None if got is None
                        else (got[0], got[1])
                    )
                else:
                    got = self.model_pool.peek_staged_match(model)
                    staged = None if got is None else (got[1], got[2])
            from ..models import hf as hf_models

            params_full = hf_models.estimate_param_bytes(model_cfg)
            if staged is not None:
                nbytes, tier = staged
                stream_bytes = int(nbytes)
                if tier == "disk":
                    read_bytes = stream_bytes
            else:
                tier = "cold"
                stream_bytes = params_full
                read_bytes = params_full
        h2d_s, m_in = book.seconds_for("coldload.h2d", stream_bytes)
        read_s, m_read = (0.0, True)
        if read_bytes:
            read_s, m_read = book.seconds_for("coldload.read", read_bytes)
        # the streaming loaders overlap read with H2D; the offload runs
        # first (sleep, then build)
        predicted_s = d2h_s + max(h2d_s, read_s)
        return {
            **out,
            "tier": tier,
            # what the swap metrics will report as bytes_moved: the
            # offload's wire bytes plus the build's bytes_in (streamed
            # params at full precision once placed, plus the KV pool)
            "predicted_bytes": offload_wire + park_out + params_full
            + kv_bytes,
            "predicted_bytes_out": offload_wire + park_out,
            "predicted_bytes_in": params_full + kv_bytes,
            "predicted_kv_pageout_bytes": park_out,
            "predicted_stream_bytes": stream_bytes,
            "predicted_s": round(predicted_s, 6),
            "predicted_d2h_s": round(d2h_s, 6),
            "predicted_h2d_s": round(h2d_s, 6),
            "predicted_read_s": round(read_s, 6),
            "measured": bool(m_out and m_in and m_read),
            # first-touch compile rides under the transfer when AOT
            # warmup is on (docs/perf.md "Warmup and the executable
            # pool"); reported as its own estimate, not added to
            # predicted_s
            "compile_estimate_s": round(compile_est, 6),
        }

    def price_sleep(self) -> Dict[str, Any]:
        """Predicted cost of a level-1 sleep of the current runtime."""
        if self.sleeper.is_sleeping:
            return {
                "kind": "sleep",
                "model": self.args.model,
                "predicted_bytes": 0,
                "predicted_s": 0.0,
                "measured": True,
            }
        wire = self._offload_wire_bytes()
        # zero-drain: the offload excludes the KV pool (peek_state) and
        # the park pages the live pages out instead — both legs priced
        park = self._park_pageout_bytes()
        s, measured = self.costs.bandwidths.seconds_for(
            "sleep.d2h", wire + park
        )
        return {
            "kind": "sleep",
            "model": self.args.model,
            "predicted_bytes": wire + park,
            "predicted_kv_pageout_bytes": park,
            "predicted_s": round(s, 6),
            "measured": measured,
        }

    def price_wake(self) -> Dict[str, Any]:
        """Predicted cost of waking the current runtime: the slept host
        payload's H2D for level 1, a checkpoint reload estimate for
        level 2."""
        sl = self.sleeper
        if not sl.is_sleeping:
            return {
                "kind": "wake",
                "model": self.args.model,
                "predicted_bytes": 0,
                "predicted_s": 0.0,
                "measured": True,
            }
        if int(sl.level) == 1:
            wire = sl.stats.bytes_offloaded
            # a parked bundle's KV pages back in with the wake (bytes
            # frozen while asleep, so this prediction is exact)
            pb = getattr(self._runtime, "parked", None)
            park_in = pb.kv_nbytes if pb is not None else 0
            s, measured = self.costs.bandwidths.seconds_for(
                "wake.h2d", wire + park_in
            )
            return {
                "kind": "wake",
                "model": self.args.model,
                "predicted_bytes": wire + park_in,
                "predicted_kv_pagein_bytes": park_in,
                "predicted_s": round(s, 6),
                "measured": measured,
            }
        # level 2: the wake re-reads weights (reinit) — a cold load
        model_cfg = self.engine.cfg.model
        from ..models import hf as hf_models

        est = hf_models.estimate_param_bytes(model_cfg)
        h2d_s, m1 = self.costs.bandwidths.seconds_for("coldload.h2d", est)
        read_s, m2 = self.costs.bandwidths.seconds_for(
            "coldload.read", est
        )
        return {
            "kind": "wake",
            "model": self.args.model,
            "predicted_bytes": est,
            "predicted_s": round(max(h2d_s, read_s), 6),
            "measured": bool(m1 and m2),
        }

    def costs_view(
        self, extra: "tuple | list" = ()
    ) -> Dict[str, Any]:
        """GET /v1/costs: every candidate actuation priced in ONE call —
        the resident model, every pooled/prefetched entry, every
        tier-resolvable evicted manifest, plus caller-named extras —
        with the bandwidth book behind the predictions. The scheduler's
        cost input, next to /v1/stats (demand) and the launcher ledger
        (state)."""
        candidates: List[Dict[str, Any]] = []
        seen = set()
        # shared across every candidate: the outgoing leg is the same
        # current runtime, so flatten/plan it once per view, not per row
        exec_desc = self.exec_pool.describe()
        try:
            offload_wire: Optional[int] = self._offload_wire_bytes()
        except Exception:  # noqa: BLE001 — e.g. sleeping: rows degrade per-candidate
            offload_wire = None

        def add(model: str, ckpt: str) -> None:
            key = (model, ckpt)
            if key in seen:
                return
            seen.add(key)
            try:
                candidates.append(
                    self.price_swap(
                        model, ckpt,
                        _offload_wire=offload_wire,
                        _exec_desc=exec_desc,
                    )
                )
            except Exception as e:  # noqa: BLE001 — one bad row never 500s the view
                candidates.append(
                    {
                        "model": model,
                        "checkpoint_dir": ckpt,
                        "error": f"{type(e).__name__}: {e}",
                    }
                )

        add(self.args.model, self.checkpoint_dir)
        for key in self.model_pool.models():
            name, _, ck = key.partition("@")
            add(name, ck)
        for key in self.model_pool.staged_keys():
            name, _, ck = key.partition("@")
            add(name, ck)
        for model, ckpt in extra:
            add(model, ckpt or "")
        # the coresident tier: every swap candidate re-priced as a
        # delta-only attach (near-zero vs its full swap row above), plus
        # zero-cost detach rows for the attached set — the scheduler
        # compares route-per-request against swap-per-burst from one view
        coresident: List[Dict[str, Any]] = []
        if self._resident_variants_cap > 1:
            for model, ckpt in seen:
                if model == self.args.model:
                    continue
                try:
                    coresident.append(self.price_attach(model, ckpt))
                except Exception as e:  # noqa: BLE001 — one bad row never 500s the view
                    coresident.append(
                        {
                            "kind": "attach",
                            "model": model,
                            "checkpoint_dir": ckpt,
                            "error": f"{type(e).__name__}: {e}",
                        }
                    )
            for model in sorted(self._residents):
                coresident.append(self.price_detach(model))
        return {
            "model": self.args.model,
            "is_sleeping": self.sleeper.is_sleeping,
            "quant": self._sleep_quant,
            "content_hash": self._content_hash,
            "bandwidth_gibps": self.costs.bandwidths.describe(),
            "sleep": self.price_sleep(),
            "wake": self.price_wake(),
            "migrate": self._price_migrate_row(),
            "compile": {
                "mean_compile_s": exec_desc.get("mean_compile_s", 0.0),
                "compiles_total": exec_desc.get("compiles_total", 0),
            },
            "candidates": candidates,
            "coresident": coresident,
        }

    def _price_migrate_row(self) -> Dict[str, Any]:
        """price_migrate, degraded to an error row instead of 500ing the
        whole /v1/costs view (the sleep/wake row discipline)."""
        try:
            return self.price_migrate()
        except Exception as e:  # noqa: BLE001 — one bad row never 500s the view
            return {"kind": "migrate", "error": f"{type(e).__name__}: {e}"}

    def actuations_view(
        self, n: int = 0, kind: Optional[str] = None
    ) -> Dict[str, Any]:
        """GET /v1/actuations: the decision flight recorder — one
        structured record per actuation this process performed, oldest
        first, plus the oracle-accuracy summary /v1/stats mirrors."""
        return {
            "records": self.costs.recorder.records(n=n, kind=kind),
            "summary": self.costs.recorder.summary(),
        }

    def _record_actuation(
        self,
        kind: str,
        model: str,
        trigger: str,
        tier: str,
        pred: Optional[Dict[str, Any]],
        actual_bytes: int,
        actual_s: float,
        outcome: str = "committed",
        extra: Optional[Dict[str, Any]] = None,
    ):
        """Flight-recorder + metrics choke point: every actuation edge
        lands one record (prediction attached when the oracle priced it
        pre-transfer) and refreshes the per-kind prediction gauges.
        ``extra`` carries structured per-actuation context — zero-drain
        records use it for ``preempted``/``resumed`` counts, so
        /v1/actuations shows what each swap displaced."""
        rec = self.costs.record(
            kind=kind,
            model=model,
            trigger=trigger,
            tier=tier,
            outcome=outcome,
            actual_bytes=actual_bytes,
            actual_s=actual_s,
            extra=extra,
            predicted_bytes=(
                None if pred is None else pred.get("predicted_bytes")
            ),
            predicted_s=(
                None if pred is None else pred.get("predicted_s")
            ),
            measured=bool(pred and pred.get("measured")),
        )
        if rec.predicted_bytes is not None:
            ENGINE_PREDICTED_BYTES.labels(kind=kind).set(
                rec.predicted_bytes
            )
        if rec.seconds_error_ratio is not None and rec.measured:
            ENGINE_COST_ERROR.labels(kind=kind).set(
                rec.seconds_error_ratio
            )
        return rec

    # -- co-resident sibling variants (docs/perf.md "Co-resident sibling
    # variants"): POST /v1/residents attach/detach, admission, pricing ------

    def _resident_id(self, model: str, checkpoint_dir: str = "") -> str:
        """A resident's routing identity: the pool key
        (``model@checkpoint_dir``) when a checkpoint qualifies it, else
        the bare model name — sibling checkpoints of the SAME named
        model (the fleet's variant-i layout) must be distinguishable
        both in the registry and in a request body's ``model`` field."""
        return (
            _pool_key(model, checkpoint_dir) if checkpoint_dir else model
        )

    def _base_resident_id(self) -> str:
        """The live base's identity in the same namespace (variant 0)."""
        return self._resident_id(
            self.args.model, getattr(self.args, "checkpoint_dir", "") or ""
        )

    def _resident_source(
        self, model: str, checkpoint_dir: str = ""
    ) -> Tuple[Optional[Dict[str, str]], str]:
        """Resolve a variant candidate's flat digest map WITHOUT
        consuming any tier state: ``(digests, tier)`` where tier is
        ``"pool"`` (slept pooled runtime), ``"prefetched"`` (staged host
        weights), or ``"disk"`` (an evicted manifest whose chunks the
        tiers can still serve) — or ``(None, "cold")``: the attach path
        rejects rather than cold-read a checkpoint (prefetch first, or
        swap)."""
        entry = (
            self.model_pool.peek(_pool_key(model, checkpoint_dir))
            if checkpoint_dir
            else self.model_pool.peek_match(model)
        )
        if entry is not None:
            digests = getattr(entry.runtime, "digests", None)
            if digests:
                tier = (
                    "prefetched"
                    if isinstance(entry.runtime, _PrefetchedWeights)
                    else "pool"
                )
                return dict(digests), tier
        if checkpoint_dir:
            man = self.model_pool.staged_manifest(
                _pool_key(model, checkpoint_dir)
            )
        else:
            got = self.model_pool.staged_manifest_match(model)
            man = got[1] if got is not None else None
        if man:
            return man, "disk"
        return None, "cold"

    def _variant_delta_keys(
        self, digests: Dict[str, str]
    ) -> Tuple[List[str], int, int]:
        """Digest-diff a variant's flat map against the live base:
        ``(delta_keys, delta_bytes, shared_bytes)``. Byte figures come
        from the BASE engine's device leaves (attach validates each
        delta leaf to the base leaf's shape+dtype, so this sizing is
        exact by construction — the same reason delta-swap byte
        predictions are). Key-set drift (a leaf only one side has) is a
        structural mismatch, not a delta: siblings share architecture."""
        base = self._runtime.digests
        if not base:
            raise ValueError(
                "the live base model carries no content digests "
                "(random-init or quantized build): co-residency needs "
                "the digest diff"
            )
        drift = set(base).symmetric_difference(digests)
        if drift:
            raise ValueError(
                f"variant is not a sibling of {self.args.model}: "
                f"{len(drift)} weight keys differ structurally "
                f"(e.g. {sorted(drift)[:4]})"
            )
        from .engine import _leaf_at

        delta_keys: List[str] = []
        delta_bytes = 0
        shared_bytes = 0
        for k, d in digests.items():
            n = int(_leaf_at(self.engine.params, k).nbytes)
            if base.get(k) != d:
                delta_keys.append(k)
                delta_bytes += n
            else:
                shared_bytes += n
        return delta_keys, delta_bytes, shared_bytes

    def price_attach(
        self, model: str, checkpoint_dir: str = ""
    ) -> Dict[str, Any]:
        """Pre-transfer pricing of a co-resident attach: delta wire
        bytes from the digest diff (byte-exact by construction — the
        same ``plan_swap`` arithmetic, minus the outgoing leg a swap
        would pay) and seconds from the ``coresident.h2d`` bandwidth
        EWMA (h2d family fallback before its first measurement).
        Read-only: nothing is fetched, nothing moves."""
        digests, tier = self._resident_source(model, checkpoint_dir)
        rid = self._resident_id(model, checkpoint_dir)
        out: Dict[str, Any] = {
            "kind": "attach",
            "model": rid,
            "checkpoint_dir": checkpoint_dir,
            "tier": "coresident",
            "source_tier": tier,
        }
        if rid in self._residents:
            return {
                **out,
                "predicted_bytes": 0,
                "predicted_s": 0.0,
                "measured": True,
                "attached": True,
            }
        if digests is None:
            raise ValueError(
                f"{model!r} is not resolvable from the pool or disk "
                "tiers; prefetch it first (POST /v1/prefetch) or swap"
            )
        delta_keys, delta_bytes, shared_bytes = self._variant_delta_keys(
            digests
        )
        s, measured = self.costs.bandwidths.seconds_for(
            "coresident.h2d", delta_bytes
        )
        return {
            **out,
            "predicted_bytes": delta_bytes,
            "predicted_s": round(s, 6),
            "predicted_delta_leaves": len(delta_keys),
            "predicted_shared_bytes": shared_bytes,
            "measured": measured,
        }

    def price_detach(self, model: str) -> Dict[str, Any]:
        """Pricing a detach: zero wire bytes — the delta's host copy
        never left the content-addressed tiers, so dropping the device
        leaves moves nothing (the near-zero actuation co-residency
        exists to buy)."""
        return {
            "kind": "detach",
            "model": model,
            "tier": "coresident",
            "predicted_bytes": 0,
            "predicted_s": 0.0,
            "measured": True,
        }

    def residents_view(self) -> Dict[str, Any]:
        """GET /v1/residents: the resident set, its budget, and the
        shared-base dedup accounting (what the launcher ledger and the
        fleet rollup carry)."""
        # lock-free snapshot (GIL-atomic dict reads): callers include
        # paths already holding the step lock
        used = self.engine.variant_hbm_bytes()
        rows = {m: dict(info) for m, info in self._residents.items()}
        return {
            "base": self.args.model,
            "resident_variants": 1 + len(rows),
            "resident_variants_cap": self._resident_variants_cap,
            "variant_hbm_budget_bytes": self._variant_hbm_budget,
            "variant_hbm_bytes": used,
            "residents": rows,
            "ledger": self.resident_ledger.describe(),
        }

    def attach_resident(
        self, model: str, checkpoint_dir: str = ""
    ) -> Dict[str, Any]:
        """POST /v1/residents: attach `model` as a device-resident
        sibling variant of the live base — upload ONLY the delta leaves
        (digest diff), share every matching base tensor in place, and
        route per-request from then on. Admission is explicit: over the
        ``--resident-variants`` cap or the ``--variant-hbm-mib`` budget
        raises :class:`ResidentRejected` (HTTP 409) and the caller falls
        back to the swap path — never OOM."""
        pred: Optional[Dict[str, Any]] = None
        try:
            pred = self.price_attach(model, checkpoint_dir)
        except Exception:  # noqa: BLE001 — pricing must never block the verb
            pred = None
        with tracing.span(
            "engine.attach_resident", model=model, base=self.args.model
        ) as sp:
            if pred is not None:
                sp.set(
                    predicted_bytes=pred.get("predicted_bytes"),
                    predicted_s=pred.get("predicted_s"),
                )
            try:
                out = self._attach_resident_impl(
                    model, checkpoint_dir, pred
                )
            except ResidentRejected as e:
                ENGINE_RESIDENT_EVENTS.labels(event="reject").inc()
                self._record_actuation(
                    "attach", model, trigger="client", tier="coresident",
                    pred=pred, actual_bytes=0, actual_s=0.0,
                    outcome="rejected", extra={"reason": str(e)},
                )
                raise
            sp.set(handle=out.get("handle"))
            return out

    def _attach_resident_impl(
        self,
        model: str,
        checkpoint_dir: str,
        pred: Optional[Dict[str, Any]],
    ) -> Dict[str, Any]:
        if self.is_follower or self.is_gang:
            raise ValueError(
                "co-resident variants are not supported for multi-host "
                "gangs"
            )
        if self._resident_variants_cap <= 1:
            raise ValueError(
                "co-residency is off (--resident-variants 1); restart "
                "with --resident-variants N and --packed-serving on"
            )
        rid = self._resident_id(model, checkpoint_dir)
        with self._admin_lock():
            if self.sleeper.is_sleeping:
                raise ValueError(
                    "engine is sleeping; wake_up before attaching "
                    "residents"
                )
            if rid == self._base_resident_id():
                raise ValueError(
                    f"{rid!r} is the live base model (variant 0); "
                    "nothing to attach"
                )
            if rid in self._residents:
                # idempotent: the resident set is declarative state
                return {
                    **self.residents_view(),
                    "model": rid,
                    "handle": self._residents[rid]["handle"],
                    "attached": False,
                }
            if 1 + len(self._residents) >= self._resident_variants_cap:
                raise ResidentRejected(
                    f"resident-set cap reached "
                    f"({self._resident_variants_cap} including the "
                    "base); detach a variant or use the swap path"
                )
            digests, tier = self._resident_source(model, checkpoint_dir)
            if digests is None:
                raise ResidentRejected(
                    f"{rid!r} is not resolvable from the pool or disk "
                    "tiers; prefetch it first (POST /v1/prefetch) or "
                    "swap"
                )
            delta_keys, delta_bytes, shared_bytes = (
                self._variant_delta_keys(digests)
            )
            if not delta_keys:
                raise ValueError(
                    f"{rid!r} is byte-identical to the live base "
                    "(empty digest diff); route to the base instead"
                )
            used = self.engine.variant_hbm_bytes()
            if (
                self._variant_hbm_budget
                and used + delta_bytes > self._variant_hbm_budget
            ):
                raise ResidentRejected(
                    f"variant delta ~{delta_bytes >> 20} MiB would "
                    f"exceed --variant-hbm-mib "
                    f"({self._variant_hbm_budget >> 20} MiB, "
                    f"{used >> 20} MiB in use); detach a variant or "
                    "use the swap path"
                )
            chunks = self.model_pool.chunks
            delta: Dict[str, Any] = {}
            for k in delta_keys:
                arr = (
                    chunks.fetch(digests[k])
                    if chunks is not None
                    else None
                )
                if arr is None:
                    raise ResidentRejected(
                        f"variant leaf {k!r} is not resolvable from "
                        "the host/disk tiers (evicted past the disk "
                        "budget, or staged quantized); prefetch "
                        f"{rid!r} or use the swap path"
                    )
                delta[k] = arr
            t0 = time.monotonic()
            handle = self.engine.attach_variant(delta, label=rid)
            dt = time.monotonic() - t0
            wire = sum(int(a.nbytes) for a in delta.values())
            self.costs.observe_transfer("coresident.h2d", wire, dt)
            from .engine import _leaf_at

            # shared leaves sized from the base's device tensors (the
            # exact bytes a full copy would have re-paid); accumulate per
            # digest — content-identical leaves (e.g. two norm scales
            # initialized alike) are distinct device tensors, so their
            # bytes must not collapse into one ledger entry
            shared_map: Dict[str, int] = {}
            for k, d in digests.items():
                if k not in delta:
                    shared_map[d] = shared_map.get(d, 0) + int(
                        _leaf_at(self.engine.params, k).nbytes
                    )
            delta_map: Dict[str, int] = {}
            for k, a in delta.items():
                delta_map[digests[k]] = delta_map.get(
                    digests[k], 0
                ) + int(a.nbytes)
            self.resident_ledger.attach(
                rid, shared=shared_map, deltas=delta_map
            )
            self._residents[rid] = {
                "handle": handle,
                "model": model,
                "checkpoint_dir": checkpoint_dir,
                "nbytes": wire,
                "delta_leaves": len(delta),
                "shared_bytes": shared_bytes,
                "source_tier": tier,
                "attached_at": time.time(),
            }
            self._variant_models[handle] = rid
        with self._slo_mu:
            self._actuations["attach"] = (
                self._actuations.get("attach", 0) + 1
            )
        ENGINE_RESIDENT_EVENTS.labels(event="attach").inc()
        self._observe_residents()
        rec = self._record_actuation(
            "attach", rid, trigger="client", tier="coresident",
            pred=pred, actual_bytes=wire, actual_s=dt,
            extra={
                "source_tier": tier,
                "handle": handle,
                "delta_leaves": len(delta),
                "shared_bytes": shared_bytes,
            },
        )
        return {
            **self.residents_view(),
            "model": rid,
            "handle": handle,
            "attached": True,
            "wire_bytes": wire,
            "attach_s": round(dt, 6),
            "source_tier": tier,
            "costs": rec.as_dict(),
        }

    def detach_resident(
        self, model: str, checkpoint_dir: str = ""
    ) -> Dict[str, Any]:
        """DELETE /v1/residents: drop a variant's device delta leaves.
        Zero wire bytes — the host tiers still hold every chunk by
        content, so a re-attach is another delta-only upload and a full
        swap back remains possible. Refused (409) while the variant has
        live or queued work."""
        rid = self._resident_id(model, checkpoint_dir)
        pred = self.price_detach(rid)
        with tracing.span(
            "engine.detach_resident", model=rid
        ) as sp:
            with self._admin_lock():
                info = self._residents.get(rid)
                if info is None:
                    raise ValueError(
                        f"{rid!r} is not an attached resident; "
                        f"attached: {sorted(self._residents)}"
                    )
                handle = info["handle"]
                t0 = time.monotonic()
                try:
                    freed = self.engine.detach_variant(handle)
                except ValueError as e:
                    raise ResidentRejected(str(e))
                dt = time.monotonic() - t0
                del self._residents[rid]
                self._variant_models.pop(handle, None)
                self.resident_ledger.detach(rid)
            # after the registry drop: the live-set guard must see the
            # variant as gone, or its gauge series would survive forever
            self._retire_model_series(rid)
            with self._slo_mu:
                self._actuations["detach"] = (
                    self._actuations.get("detach", 0) + 1
                )
            ENGINE_RESIDENT_EVENTS.labels(event="detach").inc()
            self._observe_residents()
            rec = self._record_actuation(
                "detach", rid, trigger="client", tier="coresident",
                pred=pred, actual_bytes=0, actual_s=dt,
                extra={"handle": handle, "freed_bytes": freed},
            )
            sp.set(freed_bytes=freed)
            return {
                **self.residents_view(),
                "model": rid,
                "detached": True,
                "freed_bytes": freed,
                "detach_s": round(dt, 6),
                "costs": rec.as_dict(),
            }

    def _observe_residents(self) -> None:
        """Mirror the resident set into its gauges (attach/detach edges
        and swap installs both route here)."""
        ENGINE_RESIDENT_VARIANTS.set(1 + len(self._residents))
        ENGINE_VARIANT_HBM_BYTES.set(self.engine.variant_hbm_bytes())
        ENGINE_CORESIDENT_SAVED_BYTES.set(
            self.resident_ledger.bytes_saved()
        )

    def resolve_request_model(self, model: Optional[str]) -> int:
        """Per-request routing (docs/engine.md "/v1/residents"): a
        completions body's ``model`` resolves to a variant handle — the
        base (0), an attached resident, or a 400 naming the live set.
        Empty/None routes to the base (the pre-coresidency contract)."""
        if (
            not model
            or model == self.args.model
            or model == self._base_resident_id()
        ):
            return 0
        info = self._residents.get(model)
        if info is not None:
            return info["handle"]
        raise ValueError(
            f"model {model!r} is not resident on this engine "
            f"(base: {self._base_resident_id()!r}, residents: "
            f"{sorted(self._residents)}); attach it via POST "
            "/v1/residents or swap"
        )

    def swap(
        self, model: str, checkpoint_dir: str = "", request_id: str = ""
    ) -> Dict[str, Any]:
        """Traced entry for the hot-swap verb: the span adopts whatever
        context the caller established (the HTTP handler's remote
        ``traceparent``), so the engine-side swap tree hangs off the
        launcher's RPC span in one coherent trace. The span carries the
        oracle's pre-transfer prediction (``predicted_bytes`` /
        ``predicted_s``), so every actuation trace records prediction
        vs actual."""
        pred: Optional[Dict[str, Any]] = None
        try:
            pred = self.price_swap(model, checkpoint_dir)
        except Exception:  # noqa: BLE001 — pricing must never block the verb
            pred = None
        with tracing.span(
            "engine.swap",
            model=model,
            previous=self.args.model,
            request_id=request_id,
        ) as sp:
            if pred is not None:
                sp.set(
                    predicted_bytes=pred.get("predicted_bytes"),
                    predicted_s=pred.get("predicted_s"),
                    predicted_tier=pred.get("tier"),
                )
            def record_failure(outcome: str) -> None:
                # the flight recorder must show every failed edge —
                # crash-loop churn is exactly what it exists to audit
                self._record_actuation(
                    "swap", model, trigger="client",
                    tier=pred.get("tier", "") if pred else "",
                    pred=pred, actual_bytes=0, actual_s=0.0,
                    outcome=outcome,
                )

            try:
                out = self._swap_impl(model, checkpoint_dir, request_id)
            except SwapRolledBack:
                record_failure("rolled_back")
                raise
            except ValueError as e:
                # usually a request rejection (unknown model, sleeping
                # engine) — nothing actuated, nothing to record. But a
                # cold BUILD can also raise ValueError subclasses after
                # the outgoing model already slept and rolled back:
                # _swap_impl marks those exceptions (the marker stays
                # true across identical retries, where the degraded
                # message alone would compare equal and hide the churn).
                if getattr(e, "fma_swap_actuated", False):
                    record_failure("failed")
                raise
            except Exception:
                record_failure("failed")
                raise
            sp.set(
                pool_hit=bool(out.get("pool_hit")),
                swapped=bool(out.get("swapped")),
            )
            if out.get("swapped") and not out.get("replayed"):
                for phase, key in (
                    # the *_transfer_s keys carry the pure windows on
                    # every tier (cold swaps' d2h_s is the whole
                    # outgoing sleep verb)
                    ("d2h", "d2h_transfer_s"),
                    ("h2d", "h2d_transfer_s"),
                    ("total", "swap_total_s"),
                ):
                    ENGINE_ACTUATION_SECONDS.labels(
                        kind="swap", phase=phase
                    ).observe(max(0.0, out.get(key, 0.0)))
                zd = out.get("zero_drain") or {}
                if zd.get("restore_shortfall") or zd.get("fallback"):
                    # the prediction modeled a park/resume that didn't
                    # happen as priced: a fallback swap aborted instead
                    # of parking (so the outgoing offload moved the full
                    # pool the peek excluded), or the page-in fell short
                    # (dropped clients / a rolled-back restore). Record
                    # unpriced — the oracle is blameless and a scored
                    # miss would read as digest drift.
                    pred = None
                rec = self._record_actuation(
                    "swap", model, trigger="client",
                    tier=out.get("tier", ""),
                    pred=pred,
                    actual_bytes=out.get("bytes_moved", 0),
                    actual_s=out.get("swap_total_s", 0.0),
                    # what this swap displaced / brought back: the
                    # flight recorder's preemption audit trail
                    extra=(
                        {
                            "preempted": zd.get("parked", 0),
                            "resumed": zd.get("resumed", 0),
                        }
                        if zd
                        else None
                    ),
                )
                out["costs"] = rec.as_dict()
            return out

    def _swap_impl(
        self, model: str, checkpoint_dir: str = "", request_id: str = ""
    ) -> Dict[str, Any]:
        """Hot-swap the model this chip serves (POST /v1/swap): stream the
        current model's state to the host pool while the target's
        host-resident state streams back in, chunked and double-buffered
        (engine/sleep.py swap_states) so the two DMA directions overlap.
        Pool miss = cold build (checkpoint / HF / random init) after a
        chunked offload. No process restart, no chip release: the
        launcher's ChipLedger holder is unchanged.

        **Transactional**: a mid-transfer failure rolls back (the outgoing
        model serves again, the incoming pool entry is re-pooled) and
        raises SwapRolledBack — surfaced as a retryable 503 with /health
        still 200 (DEGRADED); only a failed rollback fails the service.

        ``request_id`` (optional, caller-chosen) makes the verb safely
        retryable across a lost response: a repeat request whose id matches
        the last committed swap replays ``last_swap`` instead of swapping
        again (the launcher's timeout-recovery path reads GET /v1/swap the
        same way)."""
        if self.is_follower or self.engine.lockstep is not None:
            raise ValueError(
                "model hot-swap is not supported for multi-host gangs"
            )
        if model.startswith("hf:"):
            if not model[3:]:
                raise ValueError("swap model hf: needs a directory path")
        elif model not in MODEL_CONFIGS:
            raise ValueError(
                f"unknown model {model!r}; known: {sorted(MODEL_CONFIGS)} "
                "or hf:<model-dir>"
            )
        with self._admin_lock():
            if (
                request_id
                and self.last_swap.get("request_id") == request_id
            ):
                # idempotent replay: this exact swap already committed and
                # the caller lost the answer (timeout / connection drop) —
                # re-executing would swap AWAY from what it asked for
                return dict(self.last_swap, replayed=True)
            previous = self.args.model
            if model == previous and (
                not checkpoint_dir or checkpoint_dir == self.checkpoint_dir
            ):
                return {
                    "model": model,
                    "previous_model": previous,
                    "checkpoint_dir": self.checkpoint_dir,
                    "swapped": False,
                    "pool": self.model_pool.describe(),
                }
            if self.sleeper.is_sleeping:
                raise ValueError(
                    "engine is sleeping; wake_up before swapping models"
                )
            if self._residents:
                # a swap would tear down the base whose tensors every
                # resident's shared leaves alias — and the offload peeks
                # don't model the variant deltas
                raise ValueError(
                    "co-resident variants attached "
                    f"({sorted(self._residents)}); detach them "
                    "(DELETE /v1/residents) before swapping the base"
                )
            t0 = time.monotonic()
            # Zero-drain (docs/perf.md "Zero-drain actuation"): preempt
            # the outgoing model's live work into a parked bundle instead
            # of aborting it — unless parking is off/ineligible, the
            # bundle would blow the pool budget (it would be evicted—and
            # aborted—immediately), or the page-out itself failed; those
            # fall back to today's abort path below, byte-for-byte.
            parked_bundle = None
            zd_fallback = ""
            if self._zero_drain_parks():
                est = (
                    self._park_pageout_bytes()
                    + self._offload_wire_bytes()
                )
                if est > self.model_pool.budget_bytes:
                    zd_fallback = (
                        f"park rejected: ~{est >> 20} MiB parked state "
                        f"exceeds --model-pool-mib "
                        f"({self.model_pool.budget_bytes >> 20} MiB)"
                    )
                    logger.warning("zero-drain %s; aborting", zd_fallback)
                else:
                    parked_bundle = self._park_current(park_pending=True)
                    if parked_bundle is None:
                        zd_fallback = "park failed (kv page-out)"
            if parked_bundle is None:
                # In-flight AND still-queued work targets the outgoing
                # model (queued prompts were validated against its
                # vocab): fail it now. An otherwise-idle engine keeps
                # its prefix cache — pages move bit-exact, so a
                # swap-back resumes with a warm cache.
                exc = RuntimeError(
                    f"aborted by model swap ({previous} -> {model})"
                )
                # drain one entry at a time: submit() appends lock-free
                # from other threads, and an iterate+clear would drop
                # (and never resolve) an entry appended mid-loop;
                # pop/append on a list are individually atomic
                while self._pending:
                    fut = self._pending.pop(0)[3]
                    if not fut.done():
                        fut.set_exception(exc)
                        # still-queued requests the swap kills count too
                        # — an entry here never reached the engine, so
                        # abort_all below can't see it
                        self._count_abort("swap")
                if self.engine.has_work():
                    self._abort_engine_work(
                        f"model swapped out for {model}", exc, cause="swap"
                    )
            outgoing = self._current_runtime()
            if parked_bundle is not None:
                # rides with the slept runtime into the pool; every
                # failure path below either resumes it (rollback to live
                # serving) or aborts it cleanly (state_loss)
                outgoing.parked = parked_bundle
            # the pool key carries the checkpoint identity: the same model
            # name from a different checkpoint is a different model. A
            # request WITHOUT a checkpoint_dir means "this model, whatever
            # source it came from" — otherwise the natural swap-back
            # {"model": X} would miss a pooled X@/ckpt and silently
            # cold-build random weights under the same name.
            if checkpoint_dir:
                entry = self.model_pool.take(
                    _pool_key(model, checkpoint_dir)
                )
            else:
                entry = self.model_pool.take_match(model)
            pool_hit = entry is not None
            prefetched = pool_hit and isinstance(
                entry.runtime, _PrefetchedWeights
            )
            # AOT warmup accounting for this swap: a slept-runtime pool
            # hit keeps its compiled programs (nothing to warm); the cold
            # and prefetched paths fill this from the build below.
            warm_stats: Optional[Dict[str, Any]] = None
            #: which tier served the incoming weights: pool (slept
            #: runtime) | prefetched (staged host weights) | disk
            #: (chunk-tier manifest reload) | cold (checkpoint/HF read)
            swap_tier = "pool" if pool_hit and not prefetched else "cold"
            if pool_hit and not prefetched:
                rt = entry.runtime
                try:
                    # Delta-aware restore (engine/sleep.py): leaves the
                    # incoming and outgoing models share by content hash
                    # never cross the device boundary — sibling
                    # fine-tunes move only their delta over PCIe.
                    metrics = swap_states(
                        outgoing.sleeper,
                        rt.sleeper,
                        bucket_bytes=self._swap_bucket_bytes,
                        out_digests=(
                            outgoing.digests if self._content_hash else None
                        ),
                        in_digests=(
                            rt.digests if self._content_hash else None
                        ),
                        quant=self._sleep_quant,
                    )
                    # swap_states's windows ARE the pure transfer
                    # windows — the phase=d2h/h2d histogram figures
                    metrics["d2h_transfer_s"] = metrics["d2h_s"]
                    metrics["h2d_transfer_s"] = metrics["h2d_s"]
                except ValueError:
                    # precondition rejections fire before any transfer:
                    # the pooled entry is still intact — put it back under
                    # ITS key (a checkpoint-less request may have matched
                    # a checkpoint-qualified entry). A zero-drain park
                    # already ran, though: put its requests back into
                    # live serving (pool rebuilt, KV paged back in)
                    self._pool_park(entry.model_id, rt, entry.nbytes)
                    self._unpark_current(outgoing)
                    raise
                except SwapRolledBack as e:
                    # mid-transfer failure, rolled back by swap_states:
                    # the outgoing model is awake and serving again and
                    # the incoming entry's host state is untouched —
                    # re-pool it, resume any parked requests (the
                    # rollback's set_state rebuilt the pool), mark
                    # DEGRADED (visible, but /health stays 200), and
                    # surface a retryable 503
                    self._pool_park(entry.model_id, rt, entry.nbytes)
                    self._unpark_current(outgoing)
                    self.degraded = (
                        f"hot-swap {previous}->{model} rolled back: {e}"
                    )
                    ENGINE_RECOVERIES.labels(
                        path="swap", outcome="rolled_back"
                    ).inc()
                    self._new_work.set()
                    logger.warning(
                        "hot-swap %s -> %s rolled back (%s); still "
                        "serving %s", previous, model, e, previous,
                    )
                    raise
                except Exception as e:
                    # rollback failed (SwapRollbackFailed) or an error
                    # outside the transactional window: device state is
                    # partially moved and unrecoverable in-process — fail
                    # the service loudly so /health flips and the
                    # controller heals us, instead of serving from
                    # half-deleted arrays. Parked futures are not in
                    # _futures, so _fail_all can't see them: abort the
                    # bundle explicitly (state_loss).
                    ENGINE_RECOVERIES.labels(
                        path="swap", outcome="rollback_failed"
                    ).inc()
                    self.failure = (
                        f"hot-swap {previous}->{model} failed "
                        f"mid-transfer: {type(e).__name__}: {e}"
                    )
                    if outgoing.parked is not None:
                        b, outgoing.parked = outgoing.parked, None
                        self._abort_parked_bundle(
                            b, previous, self.failure
                        )
                    self._fail_all(RuntimeError(self.failure))
                    raise
            else:
                # Cold build, or a prefetched-weights pool hit: stream the
                # old model out first (HBM bounded by the sleeper's bucket
                # size), then build the new one into the freed space. A
                # prefetched entry skips the checkpoint read — its staged
                # host tree streams straight to device inside the build.
                # The incoming model's AOT warmup is kicked BEFORE the
                # outgoing offload: compilation is host-CPU work over
                # abstract avals, so it rides under both DMA directions
                # (engine/exec_pool.py); pool hits make it a no-op. The
                # model is resolved ONCE here (tokenizer load included)
                # and shared with the build — a resolution failure is
                # deferred to the build, whose rollback path wakes the
                # outgoing model.
                # Disk-tier reload first: an evicted model whose chunks
                # still resolve (host chunks a pooled sibling references,
                # or verified disk-tier blobs) rebuilds from LOCAL tiers
                # — no checkpoint re-read. Any unresolvable chunk made
                # take_staged a miss, so this is all-or-nothing.
                tier_params = tier_digests = None
                tier_ckpt = checkpoint_dir
                tier_src = "disk"
                if not pool_hit and self._content_hash:
                    if checkpoint_dir:
                        got = self.model_pool.take_staged(
                            _pool_key(model, checkpoint_dir)
                        )
                        if got is not None:
                            tier_params, tier_digests, tier_src = got
                    else:
                        got = self.model_pool.take_staged_match(model)
                        if got is not None:
                            tier_params, tier_digests, mkey, tier_src = got
                            tier_ckpt = (
                                mkey.split("@", 1)[1] if "@" in mkey else ""
                            )
                if prefetched:
                    swap_tier = "prefetched"
                elif tier_params is not None:
                    # "host": every chunk was still host-resident via a
                    # sibling's references; "disk": at least one verified
                    # disk-tier reload — the per-tier cost signal must not
                    # attribute DRAM-speed rebuilds to the disk tier
                    swap_tier = tier_src
                resolved = None
                try:
                    resolved = self._resolve_model(model)
                except Exception:  # noqa: BLE001 — the build re-raises it
                    pass
                warm = self._start_warmup(model, resolved=resolved)
                if warm is not None:
                    warm.window_start = time.monotonic()
                try:
                    self.sleeper.sleep(1)
                except Exception as off_exc:
                    # the outgoing offload failed before the build even
                    # started: don't leave the warmup thread compiling for
                    # a swap that is already dead (each retry would kick
                    # another, stacking orphan compile threads)
                    if warm is not None:
                        warm.abort()
                    if outgoing.parked is not None:
                        # a partial offload has no rollback (plain sleep
                        # is not transactional): the parked requests
                        # cannot reliably resume — abort them cleanly
                        b, outgoing.parked = outgoing.parked, None
                        self._abort_parked_bundle(
                            b, previous,
                            f"preempted requests lost: outgoing offload "
                            f"failed mid-swap ({type(off_exc).__name__}: "
                            f"{off_exc})",
                        )
                    # real actuation happened (a partial offload): the
                    # flight recorder must see it even for ValueError-
                    # class failures (see swap()'s handler)
                    off_exc.fma_swap_actuated = True
                    raise
                try:
                    if prefetched:
                        rt = self._build_runtime(
                            model,
                            entry.runtime.checkpoint_dir,
                            staged_params=entry.runtime.params_host,
                            warmup=warm,
                            resolved=resolved,
                            staged_digests=entry.runtime.digests,
                            staged_quant=entry.runtime.quant_metas,
                        )
                    elif tier_params is not None:
                        # weights reconstructed from the chunk tiers:
                        # stream straight host -> device, digests carried
                        # through (they name the same content)
                        rt = self._build_runtime(
                            model, tier_ckpt,
                            staged_params=tier_params,
                            warmup=warm,
                            resolved=resolved,
                            staged_digests=tier_digests,
                        )
                    else:
                        rt = self._build_runtime(
                            model, checkpoint_dir, warmup=warm,
                            resolved=resolved,
                        )
                except Exception as build_exc:
                    # the outgoing model already slept for this build:
                    # whatever happens below (rollback ok or not), the
                    # exception leaving this frame describes a FAILED
                    # ACTUATION, never a request rejection — the flight
                    # recorder keys off this marker (swap()'s handler)
                    build_exc.fma_swap_actuated = True
                    if warm is not None:
                        # swap cancelled: stop compiling between programs
                        # (what already compiled stays pooled for a retry)
                        warm.abort()
                    # a failed build must not leave the chip serving nothing
                    try:
                        self.sleeper.wake_up()
                    except Exception as wake_exc:
                        # the rollback itself failed: the outgoing model
                        # cannot come back — fail the service with BOTH
                        # causes (losing the build error here would send
                        # the operator chasing the wake failure only)
                        ENGINE_RECOVERIES.labels(
                            path="swap_cold", outcome="rollback_failed"
                        ).inc()
                        self.failure = (
                            f"hot-swap {previous}->{model} build failed "
                            f"({type(build_exc).__name__}: {build_exc}) "
                            f"and the rollback wake failed "
                            f"({type(wake_exc).__name__}: {wake_exc})"
                        )
                        if outgoing.parked is not None:
                            b, outgoing.parked = outgoing.parked, None
                            self._abort_parked_bundle(
                                b, previous, self.failure
                            )
                        self._fail_all(RuntimeError(self.failure))
                        raise RuntimeError(self.failure) from build_exc
                    if prefetched:
                        # the staged host weights are untouched by a
                        # failed build: re-pool them for the next attempt
                        self._pool_park(
                            entry.model_id, entry.runtime, entry.nbytes
                        )
                    elif tier_params is not None:
                        # tier-staged weights are untouched too: re-pool
                        # them as prefetched host weights (take_staged
                        # consumed the manifest — without this, a
                        # transient build failure costs the retry a full
                        # checkpoint re-read despite every chunk sitting
                        # verified on local tiers)
                        import jax

                        nb = sum(
                            x.nbytes for x in jax.tree.leaves(tier_params)
                        )
                        self._pool_park(
                            _pool_key(model, tier_ckpt),
                            _PrefetchedWeights(
                                model_id=model,
                                checkpoint_dir=tier_ckpt,
                                params_host=tier_params,
                                nbytes=nb,
                                digests=tier_digests,
                            ),
                            nb,
                        )
                    ENGINE_RECOVERIES.labels(
                        path="swap_cold", outcome="rolled_back"
                    ).inc()
                    # the rollback wake rebuilt the outgoing engine's
                    # state (fresh pool under zero-drain): put its
                    # preempted requests back into live serving
                    self._unpark_current(outgoing)
                    self.degraded = (
                        f"hot-swap {previous}->{model} build failed; "
                        f"rolled back to {previous}: "
                        f"{type(build_exc).__name__}: {build_exc}"
                    )
                    raise
                # A pool-miss swap still transfers the whole incoming
                # model to HBM inside the build — report the build's H2D
                # window/bytes instead of zeros, so swap_overlap_frac and
                # dashboards aren't lying on misses (the overlap here is
                # the cold loader's read/H2D overlap, not a two-direction
                # DMA overlap).
                b = self._last_build_stats
                warm_stats = b.get("warmup")
                out_stats = outgoing.sleeper.stats
                cold_moved = (
                    out_stats.bytes_offloaded + b.get("bytes_in", 0)
                )
                cold_full = (
                    out_stats.bytes_offloaded_full + b.get("bytes_in", 0)
                )
                metrics = {
                    "swap_total_s": 0.0,  # finalized below
                    "d2h_s": out_stats.last_sleep_seconds,
                    "h2d_s": b.get("h2d_s", 0.0),
                    # the pure transfer windows for the phase histogram:
                    # d2h_s above is the whole outgoing sleep verb
                    # (quiesce included), which must not pollute the
                    # "transfer window" percentiles
                    "d2h_transfer_s": out_stats.last_sleep_transfer_s,
                    "h2d_transfer_s": b.get("h2d_s", 0.0),
                    "overlap_s": b.get("overlap_s", 0.0),
                    "overlap_frac": b.get("overlap_frac", 0.0),
                    "bytes_out": out_stats.bytes_offloaded,
                    "bytes_in": b.get("bytes_in", 0),
                    # full transfer in both directions: a build streams
                    # the whole incoming model regardless of content.
                    # Under --sleep-quant the OUTGOING offload still moved
                    # only payload bytes (bytes_full records the
                    # uncompressed total, same contract as swap_states).
                    "bytes_moved": cold_moved,
                    "bytes_deduped": 0,
                    "deduped_leaves": 0,
                    "quant": out_stats.last_quant,
                    "quant_leaves": 0,
                    "bytes_full": cold_full,
                    "bytes_saved_quant": max(0, cold_full - cold_moved),
                    "buckets_out": 0,
                    "buckets_in": b.get("buckets_in", 0),
                    "bucket_bytes": self._swap_bucket_bytes,
                    "peak_bytes_in_flight": 0,
                }
            evicted = self._pool_park(
                _pool_key(previous, outgoing.checkpoint_dir),
                outgoing,
                # the parked-request bundle is host state the pool must
                # byte-count like the slept weights it rides with
                nbytes=outgoing.sleeper.stats.bytes_offloaded
                + (parked_bundle.nbytes if parked_bundle else 0),
            )
            self._free_pooled(evicted, "evicted over pool budget")
            self._install_runtime(rt)
            # swap-back to a previously-parked runtime: page its KV back
            # in and resume the preempted streams mid-decode (a restore
            # failure aborts them cleanly inside _resume_parked and the
            # swap still commits — the engine serves either way)
            zd_resumed, zd_pagein, _zd_resume_s, zd_dropped, zd_short = (
                self._resume_parked(rt)
            )
            if self._zero_drain:
                metrics["kv_pageout_bytes"] = (
                    parked_bundle.kv_nbytes if parked_bundle else 0
                )
                metrics["kv_pagein_bytes"] = zd_pagein
                # parked KV is actuation payload: it counts into the
                # byte totals the oracle predicts and the record scores
                extra_kv = metrics["kv_pageout_bytes"] + zd_pagein
                if extra_kv:
                    metrics["bytes_out"] += metrics["kv_pageout_bytes"]
                    metrics["bytes_in"] += zd_pagein
                    metrics["bytes_moved"] += extra_kv
                    metrics["bytes_full"] += extra_kv
            if model != previous:
                # same-name variant swaps (sibling checkpoints) keep the
                # label series AND the arrival EWMA: the name — which is
                # what every per-model series is keyed by — didn't change,
                # so nothing went stale and demand history is still true
                self._retire_model_series(previous)
            total = time.monotonic() - t0
            metrics["swap_total_s"] = total
            ENGINE_SWAP_SECONDS.labels(model=model).observe(total)
            ENGINE_SWAPS.labels(
                model=model, source="pool" if pool_hit else "cold"
            ).inc()
            self._bump_actuation("swap")
            if pool_hit:
                ENGINE_POOL_HITS.inc()
            ENGINE_SWAP_OVERLAP_FRAC.labels(model=model).set(
                metrics.get("overlap_frac", 0.0)
            )
            ENGINE_SWAP_INFLIGHT_BYTES.labels(model=model).set(
                metrics.get("peak_bytes_in_flight", 0)
            )
            ENGINE_SWAP_DELTA_BYTES.labels(model=model, kind="moved").set(
                metrics.get("bytes_moved", 0)
            )
            ENGINE_SWAP_DELTA_BYTES.labels(model=model, kind="deduped").set(
                metrics.get("bytes_deduped", 0)
            )
            # per-mode wire-byte accounting (docs/metrics.md): what the
            # compressed path actually moved, by direction
            swap_quant = metrics.get("quant", "off") or "off"
            ENGINE_ACTUATION_BYTES.labels(mode=swap_quant, dir="d2h").inc(
                metrics.get("bytes_out", 0)
            )
            ENGINE_ACTUATION_BYTES.labels(mode=swap_quant, dir="h2d").inc(
                metrics.get("bytes_in", 0)
            )
            # a committed swap is proof the failure domain healed: clear
            # any DEGRADED marker from an earlier rolled-back attempt
            self.degraded = None
            self.last_swap = {
                "model": model,
                "previous_model": previous,
                "request_id": request_id,
                # the installed runtime's checkpoint identity (pooled
                # runtimes remember theirs): the launcher rewrites its
                # stored options from THIS, not from the request, so a
                # restart rebuilds what the chip actually serves
                "checkpoint_dir": rt.checkpoint_dir,
                "swapped": True,
                "pool_hit": pool_hit,
                # pool_hit via background prefetch: source="pool" but the
                # entry was staged weights, not a slept runtime
                "prefetched": prefetched,
                # which tier served the incoming weights (docs/perf.md
                # "Tiered weight cache and delta swap")
                "tier": swap_tier,
                # zero-drain accounting (absent with the flag off, so
                # off-mode responses are unchanged byte-for-byte):
                # what this swap displaced and what it brought back
                **(
                    {
                        "zero_drain": {
                            "parked": (
                                parked_bundle.preempted
                                if parked_bundle
                                else 0
                            ),
                            "resumed": zd_resumed,
                            "kv_pageout_bytes": metrics.get(
                                "kv_pageout_bytes", 0
                            ),
                            "kv_pagein_bytes": metrics.get(
                                "kv_pagein_bytes", 0
                            ),
                            # parked requests whose clients vanished:
                            # their pages never paged back in, so the
                            # record is scored unpriced (swap())
                            **(
                                {"dropped": zd_dropped}
                                if zd_dropped
                                else {}
                            ),
                            # page-in moved fewer bytes than the bundle
                            # predicted (dropped clients or a failed
                            # restore): unpriced record (swap())
                            **(
                                {"restore_shortfall": True}
                                if zd_short
                                else {}
                            ),
                            **(
                                {"fallback": zd_fallback}
                                if zd_fallback
                                else {}
                            ),
                        }
                    }
                    if self._zero_drain
                    else {}
                ),
                **{
                    k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in metrics.items()
                },
                "builds_total": self.builds_total,
                "pool": self.model_pool.describe(),
                # hidden-compile accounting (None on a slept-runtime pool
                # hit — its executables rode the pooled engine): what the
                # bench reports as overlap_hidden_compile_frac
                "warmup": warm_stats,
                "exec_pool": self.exec_pool.describe(),
            }
            out = dict(self.last_swap)
        self._publish_usage()
        self._new_work.set()
        logger.info(
            "hot-swapped model %s -> %s (pool_hit=%s, %.3fs, overlap %.0f%%)",
            previous, model, pool_hit, total,
            100 * metrics.get("overlap_frac", 0.0),
        )
        return out

    # -- background checkpoint prefetch --------------------------------------

    def prefetch(self, model: str, checkpoint_dir: str = "") -> Dict[str, Any]:
        """Start a background checkpoint prefetch (POST /v1/prefetch):
        stage `model`'s weights host-resident into the model pool — never
        touching HBM, I/O-throttled (--prefetch-mib-s), abortable — so the
        first-ever swap to it takes the warm (pool) path while the current
        model keeps serving. The dual-pods controller uses this to hint
        the predicted next model."""
        if self.is_follower or self.engine.lockstep is not None:
            raise ValueError("prefetch is not supported for multi-host gangs")
        if not model.startswith("hf:"):
            raise ValueError(
                "prefetch requires an hf:<model-dir> model (named configs "
                "are random-init, and Orbax checkpoints restore straight "
                "into device placement on swap)"
            )
        if checkpoint_dir:
            # Staging can only read the HF directory. Pooling HF base
            # weights under model@checkpoint_dir would make the later swap
            # silently serve them where a non-prefetched swap restores the
            # Orbax checkpoint — wrong weights, not a slow path.
            raise ValueError(
                "prefetch cannot stage an Orbax checkpoint_dir (it reads "
                "the hf: directory only); swap to the checkpoint directly"
            )
        hf_dir = model[3:]
        if not hf_dir:
            raise ValueError("prefetch model hf: needs a directory path")
        if model == self.args.model and (
            not checkpoint_dir or checkpoint_dir == self.checkpoint_dir
        ):
            raise ValueError(f"{model} is already the serving model")
        key = _pool_key(model, checkpoint_dir)
        if (
            key in self.model_pool
            if checkpoint_dir
            else self.model_pool.contains_match(model)
        ):
            return {
                "state": "already_pooled",
                "model": model,
                "checkpoint_dir": checkpoint_dir,
                "started": False,
            }
        if self._content_hash:
            # tier fast path: an evicted model whose chunks still resolve
            # (host or disk tier) stages with ZERO source reads
            got = self.model_pool.take_staged(_pool_key(model, checkpoint_dir))
            if got is not None:
                import jax

                tree, tier_digests, tier_src = got
                nbytes = sum(x.nbytes for x in jax.tree.leaves(tree))
                pw = _PrefetchedWeights(
                    model_id=model,
                    checkpoint_dir=checkpoint_dir,
                    params_host=tree,
                    nbytes=nbytes,
                    digests=tier_digests,
                )
                evicted = self._pool_park(
                    _pool_key(model, checkpoint_dir), pw, nbytes
                )
                bounced = any(v.runtime is pw for v in evicted)
                self._free_pooled(evicted, "evicted by prefetch")
                if not bounced:
                    ENGINE_PREFETCHES.labels(outcome="completed").inc()
                    ENGINE_PREFETCH_BYTES.set(nbytes)
                    self.last_prefetch = {
                        "state": "completed",
                        "model": model,
                        "checkpoint_dir": checkpoint_dir,
                        "bytes": nbytes,
                        "source": "tier",
                        "tier": tier_src,
                        "pool": self.model_pool.describe(),
                    }
                    return dict(self.last_prefetch, started=False)
        from ..models import hf as hf_models

        model_cfg = hf_models.config_from_hf(
            hf_dir, quantization=self.args.quantization or ""
        )
        # quant-aware admission: an int8/fp8-staged model occupies its
        # payload bytes, not 2x that — the estimate must agree with what
        # the worker below actually pools
        est = hf_models.estimate_param_bytes(
            model_cfg,
            transfer_quant=self._sleep_quant,
            hot_head=self._sleep_quant_hot_head,
        )
        if est > self.model_pool.budget_bytes:
            ENGINE_PREFETCHES.labels(outcome="rejected").inc()
            raise ValueError(
                f"prefetch of {model} (~{est >> 20} MiB staged) exceeds "
                f"the model pool budget "
                f"({self.model_pool.budget_bytes >> 20} MiB); raise "
                "--model-pool-mib"
            )
        with self._prefetch_mu:
            if (
                self._prefetch_thread is not None
                and self._prefetch_thread.is_alive()
            ):
                raise ValueError(
                    "a prefetch is already in progress "
                    "(DELETE /v1/prefetch aborts it)"
                )
            self._prefetch_abort = threading.Event()
            self.last_prefetch = {
                "state": "running",
                "model": model,
                "checkpoint_dir": checkpoint_dir,
                "bytes": 0,
            }
            self._prefetch_thread = threading.Thread(
                target=self._prefetch_worker,
                args=(
                    model, hf_dir, checkpoint_dir, model_cfg,
                    self._prefetch_abort,
                    # the caller's span context, captured HERE: ContextVars
                    # do not cross into the staging thread on their own
                    tracing.current_context(),
                ),
                daemon=True,
                name="prefetch",
            )
            self._prefetch_thread.start()
        return dict(self.last_prefetch, started=True)

    def _prefetch_worker(
        self, model, hf_dir, checkpoint_dir, model_cfg, abort, trace_ctx=None
    ) -> None:
        """Prefetch thread body: host-only staging (load_params with
        place=False — pure file I/O + numpy, no device/HBM touch), then
        registration in the pool under the swap's key."""
        from ..models import hf as hf_models

        worker_sp = tracing.begin(
            "engine.prefetch", parent=trace_ctx, model=model
        )
        t0 = time.monotonic()
        # Executables stage alongside weights: the warmup compiles on its
        # own thread while this one reads shards, so a first-ever swap to
        # a prefetched model finds warm weights AND warm executables in
        # the pools — fully warm, zero compile on the swap edge.
        warm = self._start_warmup(model)
        if warm is not None:
            warm.window_start = t0
        lstats = hf_models.LoadStats()
        try:
            faults.fire("prefetch.stage")
            staged = hf_models.load_params(
                hf_dir,
                model_cfg,
                place=False,
                workers=getattr(self.args, "load_workers", 0) or None,
                abort_event=abort,
                throttle_bytes_per_s=float(
                    max(0, getattr(self.args, "prefetch_mib_s", 0)) << 20
                ),
                stats=lstats,
                want_digests=self._content_hash,
            )
        except hf_models.LoadAborted:
            if warm is not None:
                warm.abort()
            ENGINE_PREFETCHES.labels(outcome="aborted").inc()
            self.last_prefetch = {
                "state": "aborted",
                "model": model,
                "checkpoint_dir": checkpoint_dir,
                "bytes": lstats.bytes_read,
            }
            worker_sp.set(state="aborted")
            worker_sp.end()
            return
        except Exception as e:  # noqa: BLE001 — surfaced via GET /v1/prefetch
            if warm is not None:
                warm.abort()
            logger.warning("prefetch of %s failed", model, exc_info=True)
            ENGINE_PREFETCHES.labels(outcome="failed").inc()
            self.last_prefetch = {
                "state": "failed",
                "model": model,
                "checkpoint_dir": checkpoint_dir,
                "error": f"{type(e).__name__}: {e}",
            }
            worker_sp.set(state="failed", error=f"{type(e).__name__}: {e}")
            worker_sp.end()
            return
        # end of the staging window the compiles could hide under — stamped
        # BEFORE joining the warmup thread below, or compile seconds spent
        # after the staging finished would count as "hidden" and the
        # reported hidden_frac would read ~1.0 regardless of actual overlap
        t_staged = time.monotonic()
        import jax

        quant_metas = None
        if self._sleep_quant != "off":
            # compressed staging (docs/perf.md "Compressed actuation"):
            # quantize host-side while no one is waiting — the pool holds
            # payload bytes (~2x models per GiB) and the consuming swap
            # streams payloads + dequantizes on device. The fp digests
            # describe content this entry no longer carries; the pool
            # interns payloads under transfer digests instead.
            from ..models import quant as transfer_quant

            plan = transfer_quant.transfer_quant_plan(
                staged, hot_head=self._sleep_quant_hot_head, prefix=""
            )
            if any(plan):
                leaves, treedef = jax.tree.flatten(staged)
                quant_metas = [None] * len(leaves)
                for i, flag in enumerate(plan):
                    if flag:
                        leaves[i], quant_metas[i] = (
                            transfer_quant.quantize_leaf_np(
                                leaves[i], self._sleep_quant
                            )
                        )
                staged = jax.tree.unflatten(treedef, leaves)
        nbytes = sum(x.nbytes for x in jax.tree.leaves(staged)) + (
            sum(m.scale_nbytes for m in quant_metas if m is not None)
            if quant_metas is not None
            else 0
        )
        pw = _PrefetchedWeights(
            model_id=model,
            checkpoint_dir=checkpoint_dir,
            params_host=staged,
            nbytes=nbytes,
            digests=(
                self._qualify_digests(
                    dict(lstats.digests) or None, model_cfg
                )
                if self._content_hash and quant_metas is None
                else None
            ),
            quant_metas=quant_metas,
            quant_mode=self._sleep_quant if quant_metas else "off",
        )
        evicted = self._pool_park(
            _pool_key(model, checkpoint_dir), pw, nbytes
        )
        bounced = any(v.runtime is pw for v in evicted)
        self._free_pooled(evicted, "evicted by prefetch")
        if bounced:
            # raced a concurrent budget change / the estimate was low: the
            # staging cannot be kept
            if warm is not None:
                # same as the aborted/failed branches: stop compiling for
                # a model that failed to stage (what compiled stays pooled)
                warm.abort()
            ENGINE_PREFETCHES.labels(outcome="rejected").inc()
            self.last_prefetch = {
                "state": "rejected",
                "model": model,
                "checkpoint_dir": checkpoint_dir,
                "bytes": nbytes,
                "error": "staged bytes exceed the model pool budget",
            }
            worker_sp.set(state="rejected")
            worker_sp.end()
            return
        warm_stats = None
        if warm is not None:
            # the staging window is the transfer the compiles hid under
            warm.wait(600)
            warm_stats = warm.overlap_stats(window_t1=t_staged)
        ENGINE_PREFETCHES.labels(outcome="completed").inc()
        ENGINE_PREFETCH_BYTES.set(nbytes)
        self.last_prefetch = {
            "state": "completed",
            "model": model,
            "checkpoint_dir": checkpoint_dir,
            "bytes": nbytes,
            # staged representation: "off" = full precision, else the
            # transfer mode the pooled payload carries
            "quant": pw.quant_mode,
            "read_s": round(lstats.read_s, 6),
            "total_s": round(time.monotonic() - t0, 6),
            "shards": lstats.shards,
            "workers": lstats.workers,
            "pool": self.model_pool.describe(),
            # executables staged alongside the weights (exec_pool.py):
            # what the first-ever swap to this model will pool-hit
            "warmup": warm_stats,
            "exec_pool": self.exec_pool.describe(),
        }
        worker_sp.set(state="completed", bytes=nbytes)
        worker_sp.end()
        logger.info(
            "prefetched %s host-resident (%.1f MiB in %.3fs)",
            model, nbytes / 2**20, time.monotonic() - t0,
        )

    def prefetch_status(self) -> Dict[str, Any]:
        return dict(self.last_prefetch)

    def abort_prefetch(self) -> Dict[str, Any]:
        """Cancel the in-flight prefetch (DELETE /v1/prefetch): readers
        observe the abort event between tensors and unwind without ever
        registering in the pool."""
        with self._prefetch_mu:
            t = self._prefetch_thread
            if t is None or not t.is_alive():
                return {
                    "aborted": False,
                    "state": self.last_prefetch.get("state", "idle"),
                }
            self._prefetch_abort.set()
        t.join(timeout=60)
        return {"aborted": True, **self.last_prefetch}

    # -- on-demand deep profiling (POST/DELETE /v1/profile) -------------------

    def start_profile(self, log_dir: str = "") -> Dict[str, Any]:
        """Start a jax.profiler capture (XLA device + host activity,
        viewable in Perfetto / TensorBoard) — the "why is THIS phase slow"
        microscope the span timeline points at. Gated to one concurrent
        capture: the profiler is process-global state."""
        import jax

        with self._profile_mu:
            if self._profile_dir is not None:
                raise ProfileConflict(
                    f"a profile capture is already running "
                    f"(log_dir={self._profile_dir}); DELETE /v1/profile "
                    "stops it"
                )
            log_dir = log_dir or os.path.join(
                "/tmp", f"fma-profile-{os.getpid()}-{int(time.time())}"
            )
            os.makedirs(log_dir, exist_ok=True)
            jax.profiler.start_trace(log_dir)
            self._profile_dir = log_dir
        logger.info("jax profiler capture started -> %s", log_dir)
        return {"profiling": True, "log_dir": log_dir}

    def stop_profile(self) -> Dict[str, Any]:
        import jax

        with self._profile_mu:
            if self._profile_dir is None:
                raise ProfileConflict("no profile capture is running")
            # stop FIRST, clear state only on success: a raising
            # stop_trace (deleted log_dir, export error) must leave the
            # capture marked running so a retried DELETE can reach the
            # still-active process-global profiler — clearing first would
            # wedge the API (409 forever, start_trace 500s) until restart
            jax.profiler.stop_trace()
            log_dir, self._profile_dir = self._profile_dir, None
        logger.info("jax profiler capture stopped (%s)", log_dir)
        return {"profiling": False, "log_dir": log_dir}

    def profile_status(self) -> Dict[str, Any]:
        with self._profile_mu:
            return {
                "profiling": self._profile_dir is not None,
                "log_dir": self._profile_dir or "",
            }

    def _make_publisher(self):
        chip_ids = [c for c in os.environ.get("FMA_CHIP_IDS", "").split(",") if c]
        if not chip_ids:
            return None
        from ..native.hbm_publisher import HbmUsagePublisher

        return HbmUsagePublisher(chip_ids)

    def _publish_usage(self) -> None:
        """Report live HBM bytes to the cooperative usage protocol so the
        requester SPI / controller budget check see this process the way the
        reference sees a CUDA process through nvidia-smi."""
        if self._publisher is None:
            return
        if self.sleeper.is_sleeping:
            self._publisher.set_uniform(0)
        else:
            state = {"p": self.engine.params, "kv": self.engine.pool.as_tuple()}
            import jax

            nbytes = sum(x.nbytes for x in jax.tree.leaves(state))
            self._publisher.set_uniform(nbytes)

    # -- engine thread -------------------------------------------------------

    def _drain_aborts(self) -> None:
        """Apply client-disconnect aborts on the engine thread (the only
        thread allowed to touch engine scheduler state)."""
        while self._abort_q:
            fut = self._abort_q.pop(0)
            # still pending? drop it before admission
            for i, entry in enumerate(self._pending):
                if entry[3] is fut:
                    self._pending.pop(i)
                    if entry[16] is not None:
                        # tail-keep: aborted lifecycles always retain
                        entry[16].finish(
                            entry[14], time.monotonic(), keep=True,
                            outcome="aborted",
                        )
                    break
            seq_id = self._fut_seq.pop(id(fut), None)
            if seq_id is not None:
                req = self._find_live_request(seq_id)
                if self.engine.abort(seq_id, reason="client disconnected"):
                    self._count_abort("client")
                    if req is not None:
                        self._finish_request_trace(
                            req, time.monotonic(), aborted=True,
                            outcome="aborted",
                        )
                self._futures.pop(seq_id, None)
            else:
                rec = self._proxied.pop(id(fut), None)
                if rec is not None:
                    # migrated-away stream whose client dropped: the
                    # claim watcher exits silently on fut.done(), so the
                    # ONE source-side client abort is counted here (the
                    # outcome was already committed as "migrated" at
                    # release), and the destination is told to abort its
                    # claim — it stops decoding and counts its own
                    # single client abort
                    self._count_abort("client")
                    self._abort_claims_async(
                        rec.get("dest", ""), [rec.get("claim", "")]
                    )
            if not fut.done():
                fut.cancel()

    def _run(self) -> None:
        while not self._stop:
            stepped = False
            try:
                with self._lock:
                    self._drain_aborts()
                    if not self.sleeper.is_sleeping:
                        while self._pending:
                            (
                                prompt, max_tokens, temperature, fut,
                                on_token, top_p, stop_seqs, presence, freq,
                                want_alts, want_plp, seed, ignore_eos,
                                logit_bias, submit_t, variant, trace,
                            ) = self._pending.pop(0)
                            try:
                                seq_id = self.engine.add_request(
                                    prompt, max_tokens, temperature,
                                    top_p=top_p, stop_seqs=stop_seqs,
                                    presence_penalty=presence,
                                    frequency_penalty=freq,
                                    on_token=on_token,
                                    want_top_logprobs=want_alts,
                                    want_prompt_logprobs=want_plp,
                                    seed=seed,
                                    ignore_eos=ignore_eos,
                                    logit_bias=logit_bias,
                                    submit_time=submit_t,
                                    variant=variant,
                                    trace=trace,
                                )
                                self._futures[seq_id] = fut
                                self._fut_seq[id(fut)] = seq_id
                            except Exception as e:
                                if trace is not None:
                                    # rejected at admission: tail-keep
                                    # (an aborted lifecycle, however
                                    # short, is exactly what to debug)
                                    trace.finish(
                                        submit_t, time.monotonic(),
                                        keep=True, outcome="rejected",
                                        error=f"{type(e).__name__}: {e}",
                                    )
                                fut.set_exception(e)
                        if self.engine.has_work():
                            for req in self.engine.step():
                                req.done_time = time.monotonic()
                                # observe BEFORE resolving: the usage
                                # block reads req.trace_id, stamped by
                                # the trace finish inside observe
                                self._observe_finished(req)
                                fut = self._futures.pop(req.seq_id, None)
                                if fut is not None:
                                    self._fut_seq.pop(id(fut), None)
                                    if not fut.done():
                                        fut.set_result(req)
                            self._observe_kv_usage()
                            self._observe_step()
                            stepped = True
            except Exception as e:  # device/runtime failure: fail loudly
                logger.exception("engine loop failed")
                self.failure = f"{type(e).__name__}: {e}"
                self._fail_all(RuntimeError(self.failure))
                return
            if stepped:
                if self._admin_waiting:
                    # hand the just-released lock to the waiting
                    # sleep/wake/swap instead of re-grabbing it hot — an
                    # unfair lock can starve the admin call for a whole
                    # generation
                    time.sleep(0.002)
                continue
            self._new_work.wait(timeout=0.05)
            self._new_work.clear()

    def _observe_finished(self, req) -> None:
        m = self.args.model
        v = getattr(req, "variant", 0)
        if v:
            # routed requests account under THEIR model label: per-model
            # SLO/goodput series stay meaningful with N residents live
            m = self._variant_models.get(v, m)
            ENGINE_ROUTED_REQUESTS.labels(model=m).inc()
        now = time.monotonic()
        if req.done_time is not None:
            # step() stamps this before resolving the future; direct
            # engine users (tests) may not have a serving loop
            now = req.done_time
        ttft = None
        if req.first_token_time is not None:
            ttft = req.first_token_time - req.submit_time
            ENGINE_TTFT.labels(model=m).observe(ttft)
        if req.first_sched_time is not None:
            # the queue leg of TTFT: submit -> first slot (prefill and
            # decode come after) — what an actuation-induced stall shows
            # up in, separately from prefill speed
            ENGINE_QUEUE_WAIT.labels(model=m).observe(
                max(0.0, req.first_sched_time - req.submit_time)
            )
        ENGINE_E2E_LATENCY.labels(model=m).observe(now - req.submit_time)
        ENGINE_PROMPT_TOKENS.labels(model=m).inc(len(req.prompt))
        gen = len(req.out_tokens)
        ENGINE_GENERATED_TOKENS.labels(model=m).inc(gen)

        # SLO judgment (docs/perf.md "Fleet benchmarking and goodput"):
        # each enabled target is judged independently; goodput counts a
        # request's tokens only when NO enabled target was violated
        # (vacuously all of them, when none is configured).
        met_all = True
        evaluated = False
        violated_slos: List[str] = []
        if self._slo_ttft_s > 0:
            ok = ttft is not None and ttft <= self._slo_ttft_s
            ENGINE_SLO_REQUESTS.labels(
                model=m, slo="ttft", outcome="met" if ok else "violated"
            ).inc()
            if not ok:
                violated_slos.append("ttft")
            met_all = met_all and ok
            evaluated = True
        if self._slo_tpot_s > 0:
            if req.first_token_time is not None and gen > 1:
                tpot = (now - req.first_token_time) / (gen - 1)
                ok = tpot <= self._slo_tpot_s
            else:
                # a single-token (or token-less error) request has no
                # inter-token interval to judge
                ok = req.first_token_time is not None
            ENGINE_SLO_REQUESTS.labels(
                model=m, slo="tpot", outcome="met" if ok else "violated"
            ).inc()
            if not ok:
                violated_slos.append("tpot")
            met_all = met_all and ok
            evaluated = True
        if met_all:
            ENGINE_GOODPUT_TOKENS.labels(model=m).inc(gen)
        violated = evaluated and not met_all
        trace_id = self._finish_request_trace(
            req, now, violated=violated,
            aborted=bool(getattr(req, "error", None)),
        )
        with self._slo_mu:
            self._finished_requests += 1
            self._generated_tokens += gen
            if met_all:
                self._goodput_tokens += gen
            if evaluated:
                if met_all:
                    self._slo_met += 1
                else:
                    self._slo_violated += 1
            if violated and trace_id:
                self._slo_exemplars.append(
                    {
                        "trace_id": trace_id,
                        "model": m,
                        "violated": violated_slos,
                        "ttft_s": None if ttft is None else round(ttft, 6),
                        "legs": {
                            k: round(v, 6)
                            for k, v in self._request_legs(
                                req, now
                            ).items()
                        },
                    }
                )

    def _request_legs(self, req, now: float) -> Dict[str, float]:
        """Decompose submit→done into the leg durations the SLO
        exemplars (and bench.py's slo_attribution) bucket by. Preemption
        wall time is INSIDE the raw queue/prefill/decode windows (the
        stamps don't pause while parked), so it is carved out — the
        pre-first-token share from queue first, then prefill; the rest
        from decode — leaving {queue, prefill, decode, preempt} a
        partition of the request's server-side wall time."""
        pre = max(0.0, getattr(req, "preempt_pre_token_s", 0.0))
        total_pre = max(0.0, getattr(req, "preempt_s", 0.0))
        if req.first_sched_time is None:
            queue = max(0.0, now - req.submit_time)
            prefill = decode = 0.0
        else:
            queue = max(0.0, req.first_sched_time - req.submit_time)
            if req.first_token_time is not None:
                prefill = max(
                    0.0, req.first_token_time - req.first_sched_time
                )
                decode = max(0.0, now - req.first_token_time)
            else:
                prefill = max(0.0, now - req.first_sched_time)
                decode = 0.0
        take = min(queue, pre)
        queue -= take
        prefill = max(0.0, prefill - (pre - take))
        decode = max(0.0, decode - (total_pre - pre))
        return {
            "queue": queue,
            "prefill": prefill,
            "decode": decode,
            "preempt": total_pre,
            "migrate": 0.0,
        }

    def _finish_request_trace(
        self,
        req,
        now: float,
        violated: bool = False,
        aborted: bool = False,
        migrated: bool = False,
        outcome: str = "finished",
    ) -> str:
        """Close out a request's lifecycle trace: decide retention
        (head-sample draw OR tail-keep on violation/abort/migration),
        record the one whole-window decode span, flush to the request
        ring, and stamp req.trace_id for the usage block. At
        --trace-requests 0 a violated/aborted request still gets a
        retained trace, synthesized here from the Request's timestamps —
        the hot path recorded nothing for it. Returns the trace_id when
        spans were retained, else ''."""
        if getattr(req, "_trace_done", False):
            # a request can reach two finish paths (engine abort, then
            # the step loop's finished list): first one wins
            return req.trace_id
        req._trace_done = True
        tr = getattr(req, "trace", None)
        if tr is None:
            if not (violated or aborted) or not tracing.enabled():
                return ""
            tr = tracing.RequestTrace(sampled=True)
            if req.first_sched_time is not None:
                tr.add(
                    "request.queue", req.submit_time, req.first_sched_time
                )
                first_tok = req.first_token_time
                tr.add(
                    "request.prefill",
                    req.first_sched_time,
                    first_tok if first_tok is not None else now,
                    prompt_tokens=len(req.prompt),
                    cached_tokens=req.cached_tokens,
                    synthesized=True,
                )
        if (
            req.first_token_time is not None
            and now > req.first_token_time
            and not migrated
        ):
            # ONE span for the whole decode window — never one per step.
            # Migrated-away requests skip it: their decode continues on
            # the destination, which records its own window.
            tr.add(
                "request.decode",
                req.first_token_time,
                now,
                tokens=len(req.out_tokens),
                finish_reason=req.finish_reason or "",
            )
        keep = tr.sampled or violated or aborted or migrated
        if aborted and outcome == "finished":
            outcome = "aborted"
        tid = tr.finish(
            req.submit_time,
            now,
            keep,
            outcome=outcome,
            violated=bool(violated),
            prompt_tokens=len(req.prompt),
            tokens=len(req.out_tokens),
            preempt_s=round(getattr(req, "preempt_s", 0.0), 6),
        )
        req.trace = None
        req.trace_id = tid if keep else ""
        return req.trace_id

    def _finish_migrate_trace(
        self, req, t0: float, now: float, dest: str, outcome: str
    ) -> str:
        """Source-side close-out for a migrated-away stream: a
        ``request.migrate`` span over the handoff window
        [export-park, release], then the lifecycle root with
        outcome=migrated — ALWAYS retained (migration forensics: a
        cross-chip stream's source half must be fetchable whatever the
        sampling draw was). The destination's spans carry the same
        trace_id, so the two exports concatenate into one timeline."""
        if getattr(req, "trace", None) is None:
            return ""
        req.trace.add(
            "request.migrate", t0, now, dest=dest or "", outcome=outcome
        )
        req.trace.sampled = True
        return self._finish_request_trace(
            req, now, migrated=True, outcome=outcome
        )

    def _observe_kv_usage(self) -> None:
        alloc = self.engine.allocator
        total = max(1, alloc.num_pages - 1)
        ENGINE_KV_USAGE.labels(model=self.args.model).set(
            (total - alloc.available) / total
        )

    def _observe_step(self) -> None:
        """Mirror per-step scheduler observability after each engine
        step: decode-slot occupancy, the packed-step token histogram,
        and pad-waste byte increments (the engine keeps cumulative
        totals; a swap installs a fresh engine whose counters restart,
        so a backwards jump resets the mirror instead of under-counting
        forever)."""
        eng = self.engine
        m = self.args.model
        ENGINE_SLOT_OCCUPANCY.labels(model=m).set(
            sum(1 for s in eng._slots if s is not None)
            / max(1, eng.cfg.max_batch)
        )
        stats = getattr(eng, "last_step_stats", None)
        if stats is not None and stats.get("mode") == "packed":
            ENGINE_PACKED_TOKENS.labels(model=m).observe(stats["tokens"])

        def mirror_path_totals(totals, seen_map, counter):
            # one delta/reset discipline for every cumulative per-path
            # engine byte dict (a swap installs a fresh engine whose
            # counters restart, so a backwards jump resets the mirror
            # instead of under-counting forever)
            for path, total in totals.items():
                seen = seen_map.get(path, 0)
                if total > seen:
                    counter.labels(model=m, path=path).inc(total - seen)
                if total != seen:
                    seen_map[path] = total

        mirror_path_totals(
            getattr(eng, "pad_waste_bytes", {}),
            self._pad_waste_seen, ENGINE_PAD_WASTE_BYTES,
        )
        mirror_path_totals(
            getattr(eng, "step_h2d_bytes", {}),
            self._step_h2d_seen, ENGINE_STEP_H2D_BYTES,
        )

    def _run_follower(self) -> None:
        """Gang follower: replay the leader's compiled calls until it
        shuts down. Exceptions fail /health so the crash relay heals us."""
        from .multihost import follower_loop

        try:
            follower_loop(self.engine, self.sleeper)
            if self.watchdog is not None:
                # clean SHUTDOWN received: the leader is about to exit on
                # purpose; don't let its disappearance read as a death
                self.watchdog.stop()
        except Exception as e:
            logger.exception("follower loop failed")
            self.failure = f"{type(e).__name__}: {e}"

    def _fail_all(self, exc: Exception) -> None:
        for entry in self._pending:
            fut = entry[3]
            if not fut.done():
                fut.set_exception(exc)
        self._pending.clear()
        for fut in self._futures.values():
            if not fut.done():
                fut.set_exception(exc)
        self._futures.clear()
        self._fut_seq.clear()

    # -- API used by handlers (event-loop thread) ---------------------------

    def queue_depth(self) -> int:
        """Waiting + in-flight request count (the HPA pressure signal)."""
        eng = self.engine
        running = sum(1 for s in eng._slots if s is not None)
        return len(self._pending) + len(eng._waiting) + running

    def stats(self) -> Dict[str, Any]:
        """One-call instance stats row (GET /v1/stats): queue depth,
        arrival-rate EWMA, SLO attainment, goodput, per-cause aborts and
        actuation counts — exactly what the launcher's fleet rollup
        aggregates across instances without parsing Prometheus text.
        Cheap and lock-bounded: safe while sleeping or under load."""
        now = time.monotonic()
        with self._slo_mu:
            met, violated = self._slo_met, self._slo_violated
            judged = met + violated
            out = {
                "model": self.args.model,
                "queue_depth": self.queue_depth(),
                "arrival_rate_rps": round(self._arrival.rate(now), 6),
                "slo": {
                    "ttft_ms": self._slo_ttft_s * 1e3,
                    "tpot_ms": self._slo_tpot_s * 1e3,
                    "met": met,
                    "violated": violated,
                    "attainment": (
                        round(met / judged, 6) if judged else None
                    ),
                },
                "finished_requests": self._finished_requests,
                "generated_tokens": self._generated_tokens,
                "goodput_tokens": self._goodput_tokens,
                "aborted": dict(self._aborted),
                "actuations": dict(self._actuations),
                "uptime_s": round(now - self.started_at, 3),
                "is_sleeping": self.sleeper.is_sleeping,
                # zero-drain preemption accounting (docs/perf.md
                # "Zero-drain actuation"): lifetime preempt/resume/abort
                # counts plus the host bytes parked KV holds right now —
                # what the fleet harness reads to prove "zero swap
                # aborts" and what the launcher rollup aggregates
                "zero_drain": {
                    "enabled": self._zero_drain,
                    "preempted": self._zd_preempted,
                    "resumed": self._zd_resumed,
                    "aborted": self._zd_aborted,
                    "migrated": self._zd_migrated,
                    "parked_kv_bytes": max(0, self._zd_parked_bytes),
                },
                # live-migration ledger (docs/operations.md "Draining a
                # node without dropping streams"): per-role terminal
                # outcomes plus the in-flight fence — what the launcher's
                # drain loop polls and the fleet rollup aggregates
                "migration": {
                    **self._mig,
                    "in_flight": bool(self._migration),
                    "imported_claims": len(self._imported_claims),
                },
                # last-N SLO-violated exemplars (docs/tracing.md): each
                # row pairs a retained trace_id with its leg-duration
                # breakdown, so "attainment dropped — which leg?" is one
                # stats read + one /v1/traces fetch
                "slo_exemplars": list(self._slo_exemplars),
            }
        # cost-oracle summary (utils/costs.py): per-kind bandwidth EWMAs
        # + last-N prediction accuracy — the fleet harness scores oracle
        # accuracy from this row without a second endpoint, and the
        # launcher's fleet rollup carries it into ledger.costs
        out["costs"] = self.costs.summary()
        # co-resident set (docs/perf.md "Co-resident sibling variants"):
        # who is routable on this engine without an actuation, and what
        # the shared base is saving — the launcher ledger's resident row
        if self._residents or self._resident_variants_cap > 1:
            out["residents"] = {
                "cap": self._resident_variants_cap,
                "attached": sorted(self._residents),
                "variant_hbm_bytes": self.engine.variant_hbm_bytes(),
                "variant_hbm_budget_bytes": self._variant_hbm_budget,
                "saved_bytes": self.resident_ledger.bytes_saved(),
            }
        return out

    def submit(
        self,
        prompt: List[int],
        max_tokens: int,
        temperature: float,
        on_token: Optional[Any] = None,
        top_p: float = 1.0,
        stop_seqs: Any = (),
        presence_penalty: float = 0.0,
        frequency_penalty: float = 0.0,
        want_top_logprobs: bool = False,
        want_prompt_logprobs: bool = False,
        seed: "int | None" = None,
        ignore_eos: bool = False,
        logit_bias: "Dict[int, float] | None" = None,
        variant: int = 0,
        trace_ctx: "tracing.SpanContext | None" = None,
    ) -> concurrent.futures.Future:
        """Enqueue a request. `on_token(req, tok)` — if given — fires on the
        engine thread for every emitted token (the streaming hook); keep it
        to an enqueue. ``variant`` routes to a co-resident sibling
        (resolve_request_model) — 0 is the base model. ``trace_ctx`` is
        the client's ``traceparent`` (completions handlers): it forces a
        lifecycle trace even at --trace-requests 0 and parents it on the
        caller's span."""
        fut: concurrent.futures.Future = concurrent.futures.Future()
        if self.is_follower:
            fut.set_exception(
                RuntimeError(
                    "multi-host gang follower: requests are served by the "
                    "gang leader (process 0)"
                )
            )
            return fut
        if self.failure is not None:
            fut.set_exception(RuntimeError(self.failure))
            return fut
        now = time.monotonic()
        with self._slo_mu:
            # demand signal, stamped at the HTTP edge: the EWMA must see
            # offered load even when the engine is saturated or asleep
            self._arrival.observe(now)
        trace = None
        if tracing.enabled() and (
            trace_ctx is not None or tracing.request_sampling() > 0.0
        ):
            # frac 0 with no client traceparent: no collector, and every
            # downstream hook is a single `is None` check (byte-inert)
            trace = tracing.RequestTrace(
                sampled=trace_ctx is not None or tracing.sample_request(),
                parent=trace_ctx,
            )
        self._pending.append(
            (prompt, max_tokens, temperature, fut, on_token, top_p, stop_seqs,
             presence_penalty, frequency_penalty, want_top_logprobs,
             want_prompt_logprobs, seed, ignore_eos, logit_bias, now,
             int(variant), trace)
        )
        self._new_work.set()
        ENGINE_QUEUE_DEPTH.labels(model=self.args.model).set(self.queue_depth())
        return fut

    def abort(self, fut: concurrent.futures.Future) -> None:
        """Client went away: stop generating for its request (vLLM's abort;
        decode cycles on a disconnected request are pure waste). Applied by
        the engine thread at the next loop iteration."""
        self._abort_q.append(fut)
        self._new_work.set()

    def sleep(self, level: int) -> Dict[str, Any]:
        pred: Optional[Dict[str, Any]] = None
        try:
            # price_sleep models the level-1 offload; a level-2 sleep
            # discards state (bytes_offloaded = 0), so it stays unpriced
            pred = self.price_sleep() if level == 1 else None
        except Exception:  # noqa: BLE001 — pricing must never block the verb
            pred = None
        with tracing.span(
            "engine.sleep", level=level, model=self.args.model
        ) as sp:
            if pred is not None:
                sp.set(
                    predicted_bytes=pred.get("predicted_bytes"),
                    predicted_s=pred.get("predicted_s"),
                )
            return self._sleep_impl(level, pred=pred)

    def _sleep_impl(
        self, level: int, pred: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        if self.is_follower:
            # a follower can't unilaterally leave the collective loop; the
            # leader's broadcast sleeps the whole gang
            return {
                "deferred": True,
                "reason": "gang follower; sleep is driven by the leader",
            }
        if level not in (1, 2):
            # validate BEFORE any broadcast: a bad level must 400 locally,
            # never reach followers (their replay would raise and kill the
            # follower loop, deadlocking the gang's next collective)
            raise ValueError("sleep level must be 1 or 2")
        with self._admin_lock():
            if self._residents:
                # the slept state has no variant dimension: an offload
                # would strand (L1) or leak (L2) the delta leaves.
                # Detach is the delta-only "offload" — zero d2h, the
                # content tiers already hold every delta chunk.
                raise ValueError(
                    "co-resident variants attached "
                    f"({sorted(self._residents)}); detach them "
                    "(DELETE /v1/residents) before sleeping"
                )
            was_sleeping = self.sleeper.is_sleeping
            prev_level = self.sleeper.level
            parked_for_sleep = None
            #: park attempted but fell back (page-out failure): the
            #: offload then moves the full pool the prediction's peek
            #: excluded — the record must go unpriced, not score a
            #: false byte miss
            zd_sleep_fallback = False
            if (
                level == 1
                and not was_sleeping
                and self._zero_drain_parks()
                and self.engine.lockstep is None
            ):
                # zero-drain: page the live requests' KV out compactly
                # BEFORE the offload — the slept state is then
                # weights-only (the full, mostly-empty pool stops
                # occupying host bytes) and wake re-seats the bundle.
                # A park failure just keeps today's full-pool offload,
                # which already preserves in-flight requests across a
                # plain L1 sleep.
                parked_for_sleep = self._park_current(park_pending=False)
                if parked_for_sleep is not None:
                    self._runtime.parked = parked_for_sleep
                else:
                    zd_sleep_fallback = True
            if self.engine.lockstep is not None:
                if level >= 2:
                    raise ValueError(
                        "level-2 sleep is not supported for multi-host "
                        "gangs (followers cannot replay the reinit)"
                    )
                self.engine.lockstep.sleep(level, self.release_on_sleep)
            if self.release_on_sleep:
                # Device release destroys the PJRT client that owns the
                # pooled models' pinned-host state and every compiled
                # executable — a later pool hit would stream from dead
                # buffers. Drop everything client-owned while the client
                # is still alive: the model pool (next swap-in
                # cold-builds), the live executable-pool entries (spilled
                # copies survive where reload is trusted), the engine's
                # installed AOT table, and the last warmup task's results
                # dict, which pins the same client-owned executables.
                # Wake re-validates the executable pool.
                if len(self.model_pool):
                    self._free_pooled(
                        self.model_pool.drain(), "device release"
                    )
                # a still-running warmup (e.g. kicked by an in-flight
                # prefetch) must be fenced BEFORE the pool drop: left
                # alone, it would finish its compile after drop_live()
                # and re-pool an executable owned by the dead client
                lw = self._last_warmup
                if lw is not None:
                    lw.abort(drop_results=True)
                    lw.wait(5)
                self.exec_pool.drop_live()
                self.engine.clear_executables()
                self._last_warmup = None
            try:
                out = self.sleeper.sleep(
                    level, release=self.release_on_sleep
                )
            except Exception as sleep_exc:
                if (
                    parked_for_sleep is not None
                    and self._runtime.parked is parked_for_sleep
                ):
                    # a failed offload has no rollback (plain sleep is
                    # not transactional) and the engine's state is
                    # indeterminate: resolve the parked futures to a
                    # clean state_loss abort instead of stranding them
                    # forever, and give the engine its pool back in
                    # case it can still serve
                    self._runtime.parked = None
                    try:
                        if self.engine.kv_detached:
                            self.engine.rebuild_kv_pool()
                    except Exception:  # noqa: BLE001 — best effort
                        logger.warning(
                            "KV pool rebuild after a failed sleep "
                            "failed", exc_info=True,
                        )
                    self._abort_parked_bundle(
                        parked_for_sleep,
                        self.args.model,
                        f"preempted requests lost: level-1 offload "
                        f"failed ({type(sleep_exc).__name__}: "
                        f"{sleep_exc})",
                    )
                raise
            if (
                int(self.sleeper.level) == 2
                and getattr(self._runtime, "parked", None) is not None
            ):
                # a level-2 edge (direct or L1->L2 escalation) drops the
                # host state a parked bundle would resume against: abort
                # the preempted requests cleanly (state_loss), exactly
                # like the state they rode with
                b, self._runtime.parked = self._runtime.parked, None
                self._abort_parked_bundle(
                    b,
                    self.args.model,
                    "preempted requests lost: level-2 sleep discarded "
                    "the parked state",
                )
        if out.get("bytes_offloaded") and not was_sleeping:
            # per-mode wire bytes: payload bytes under --sleep-quant.
            # Guarded like the actuation count below — a re-sent sleep's
            # answer still describes the ORIGINAL offload's bytes, and
            # charging them again would double wire-byte telemetry.
            ENGINE_ACTUATION_BYTES.labels(
                mode=out.get("quant", "off") or "off", dir="d2h"
            ).inc(out["bytes_offloaded"])
        if not was_sleeping or self.sleeper.level != prev_level:
            # count state CHANGES only: a fresh sleep or an L1->L2
            # escalation (real state movement — the host copy drops), but
            # never an idempotent re-sent sleep, which moved nothing and
            # must not inflate the fleet rollup's actuations/hour
            self._bump_actuation("sleep")
            sleep_s = out.get("last_sleep_seconds", 0.0)
            if not was_sleeping:
                # phase=d2h is the pure transfer window — observed only
                # when a transfer actually ran (a level-2 sleep discards
                # state; a 0.0 sample would drag the window percentiles
                # toward zero); total is the whole verb
                if int(self.sleeper.level) == 1:
                    ENGINE_ACTUATION_SECONDS.labels(
                        kind="sleep", phase="d2h"
                    ).observe(
                        max(0.0, self.sleeper.stats.last_sleep_transfer_s)
                    )
                ENGINE_ACTUATION_SECONDS.labels(
                    kind="sleep", phase="total"
                ).observe(max(0.0, sleep_s))
            sleep_priced = (
                not was_sleeping
                and not self.is_gang
                and int(self.sleeper.level) == 1
                and not zd_sleep_fallback
            )
            self._record_actuation(
                "sleep",
                self.args.model,
                # an L1->L2 transition while already asleep is the
                # escalation edge (host copy dropped), not a client-
                # driven offload
                trigger="escalation" if was_sleeping else "client",
                tier="host" if int(self.sleeper.level) == 1 else "discard",
                # escalations moved no new bytes, gang offloads stage
                # per-shard, and L2 sleeps discard instead of offload:
                # all outside the pricing model, recorded unpriced
                pred=pred if sleep_priced else None,
                # a zero-drain park's KV page-out is part of what this
                # sleep moved: the prediction (price_sleep) counts it,
                # so the actual must too or byte_exact_frac lies
                actual_bytes=out.get("bytes_offloaded", 0)
                + (
                    parked_for_sleep.kv_nbytes if parked_for_sleep else 0
                ),
                # priced records score like-for-like against the pure
                # offload window price_sleep models (the quiesce and a
                # device release are outside it); the park's d2h window
                # joins it — same link, same prediction
                actual_s=(
                    self.sleeper.stats.last_sleep_transfer_s
                    + (
                        parked_for_sleep.pageout_s
                        if parked_for_sleep
                        else 0.0
                    )
                    if sleep_priced
                    else (0.0 if was_sleeping else sleep_s)
                ),
                extra=(
                    {"preempted": parked_for_sleep.preempted}
                    if parked_for_sleep
                    else None
                ),
            )
        self._publish_usage()
        return out

    def wake_up(self) -> Dict[str, Any]:
        pred: Optional[Dict[str, Any]] = None
        try:
            pred = self.price_wake()
        except Exception:  # noqa: BLE001 — pricing must never block the verb
            pred = None
        with tracing.span("engine.wake", model=self.args.model) as sp:
            if pred is not None:
                sp.set(
                    predicted_bytes=pred.get("predicted_bytes"),
                    predicted_s=pred.get("predicted_s"),
                )
            return self._wake_up_impl(pred=pred)

    def _wake_up_impl(
        self, pred: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        if self.is_follower:
            return {
                "deferred": True,
                "reason": "gang follower; wake is driven by the leader",
            }
        with self._admin_lock():
            was_sleeping = self.sleeper.is_sleeping
            was_l1 = (
                self.sleeper.level == 1
                and not getattr(self.sleeper, "_staged", None)
            )
            if self.engine.lockstep is not None and self.sleeper.is_sleeping:
                self.engine.lockstep.wake()
            if self.sleeper.level == 2:
                # KV state is gone: abort anything mid-generation before the
                # fresh state arrives, then rebuild params+pool in place.
                self._abort_engine_work(
                    "level-2 sleep discarded state",
                    RuntimeError("aborted by level-2 sleep (KV discarded)"),
                )
                eng = self.engine
                m = eng.cfg.model

                def reinit():
                    import jax

                    from ..models import llama as _llama
                    from ..parallel.mesh import shard_pytree
                    from .kv_cache import PagePool

                    if self.checkpoint_dir:
                        # level-2 wake = reload from disk (the reference's
                        # L2 wake re-reads weights; README.md:16-26);
                        # load_params already lands on the mesh placement
                        from ..models import checkpoint as _ckpt

                        params = _ckpt.load_params(
                            self.checkpoint_dir, m, mesh=eng.mesh
                        )
                    elif self.hf_dir:
                        from ..models import hf as _hf

                        # streaming cold loader, straight onto the mesh
                        # placement (read of layer k+1 overlaps H2D of k)
                        params = _hf.load_params(
                            self.hf_dir, m, mesh=eng.mesh,
                            workers=getattr(
                                self.args, "load_workers", 0
                            ) or None,
                            max_inflight_bytes=max(
                                1,
                                getattr(
                                    self.args, "load_inflight_mib", 512
                                ),
                            ) << 20,
                        )
                    else:
                        from ..models.registry import (
                            init_params_for,
                            logical_axes_for,
                        )

                        params = init_params_for(
                            jax.random.key(self.args.seed), m
                        )
                        if eng.mesh is not None:
                            params = shard_pytree(
                                params, eng.mesh, logical_axes_for(m)
                            )
                    pool = PagePool.create(
                        m.num_layers,
                        eng.cfg.num_pages,
                        eng.cfg.page_size,
                        m.num_kv_heads,
                        m.head_dim,
                        dtype=m.dtype,
                        mesh=eng.mesh,
                    )
                    return {"params": params, "kv": pool.as_tuple()}

                out = self.sleeper.wake_up(reinit=reinit)
            else:
                out = self.sleeper.wake_up()
            # wake must not recompile: compiled programs are host-resident
            # and survive a plain sleep; after a device release the pool
            # re-validates (reinstalling spilled/pooled executables)
            # instead of recompiling
            self._reinstall_executables()
            if was_l1 and self.sleeper.stats.last_wake_bytes:
                # per-mode wire bytes the restore moved (payload bytes
                # under --sleep-quant, with the on-device dequant after)
                ENGINE_ACTUATION_BYTES.labels(
                    mode=self.sleeper.stats.last_quant or "off", dir="h2d"
                ).inc(self.sleeper.stats.last_wake_bytes)
            # zero-drain: the parked bundle's KV pages back into the
            # fresh pool and the preempted streams continue mid-decode
            # (a restore failure aborts them cleanly inside
            # _resume_parked; the engine serves either way)
            zd_resumed = zd_pagein = zd_dropped = 0
            zd_resume_s = 0.0
            zd_short = False
            if (
                was_sleeping
                and not self.sleeper.is_sleeping
                and getattr(self._runtime, "parked", None) is not None
            ):
                (
                    zd_resumed, zd_pagein, zd_resume_s, zd_dropped,
                    zd_short,
                ) = self._resume_parked(self._runtime)
        if was_sleeping:
            # a wake on an already-awake engine is a no-op, not an
            # actuation the fleet rollup should charge for
            self._bump_actuation("wake")
            wake_s = self.sleeper.stats.last_wake_seconds
            # phase=h2d is the transfer window (client reacquisition
            # excluded), observed only when a host payload actually
            # moved — a level-2 wake reinitializes instead; total is
            # the whole verb
            wake_transfer_s = self.sleeper.stats.last_wake_transfer_s
            if (was_l1 or self.is_gang) and wake_transfer_s > 0:
                # only when a host payload actually moved: an L2 wake
                # (incl. the gang case) reinitializes, and a 0.0 sample
                # would drag the transfer-window percentiles toward zero
                ENGINE_ACTUATION_SECONDS.labels(
                    kind="wake", phase="h2d"
                ).observe(wake_transfer_s)
            ENGINE_ACTUATION_SECONDS.labels(
                kind="wake", phase="total"
            ).observe(max(0.0, wake_s))
            # a page-in shortfall (dropped parked clients, or a restore
            # rolled back to the state_loss abort) makes the actual
            # bytes fall short of the (full-bundle) prediction: record
            # unpriced, like the other false-byte-miss classes (gang
            # wakes, L2 edges)
            priced = not self.is_gang and was_l1 and not zd_short
            self._record_actuation(
                "wake",
                self.args.model,
                trigger="client",
                tier="host" if was_l1 else "cold",
                # gang wakes restore per-process staged shards and L2
                # wakes reinitialize (actual h2d payload = 0): neither
                # matches the single-process L1 pricing, so both record
                # unpriced — a mismatched prediction would read as a
                # false byte-exactness miss
                pred=pred if priced else None,
                # parked-KV page-in is payload this wake moved: counted
                # like the park's page-out on the sleep record, so
                # predicted (price_wake) and actual stay byte-exact
                actual_bytes=(
                    self.sleeper.stats.last_wake_bytes + zd_pagein
                    if was_l1 or self.is_gang
                    else 0
                ),
                # a priced record scores the prediction like-for-like:
                # the transfer window (what price_wake models — client
                # reacquisition is deliberately outside it); unpriced
                # records keep the whole-verb wall
                actual_s=(
                    wake_transfer_s + zd_resume_s if priced else wake_s
                ),
                extra=(
                    {"resumed": zd_resumed} if zd_resumed else None
                ),
            )
        self._publish_usage()
        self._new_work.set()
        return out

    def shutdown(self) -> None:
        self._stop = True
        self._new_work.set()
        with self._prefetch_mu:
            t = self._prefetch_thread
            if t is not None and t.is_alive():
                self._prefetch_abort.set()
        if t is not None and t.is_alive():
            t.join(timeout=10)
        if not self.is_follower:
            # follower threads block inside the broadcast collective and
            # exit with the process (daemon); only the leader's loop joins
            self._thread.join(timeout=5)
        if self.engine.lockstep is not None:
            try:
                # under the lock: if the engine thread outlived the join
                # timeout (long compile mid-step), its frame broadcasts must
                # not interleave with the shutdown frame
                with self._lock:
                    self.engine.lockstep.shutdown()
            except Exception:
                logger.warning("lockstep shutdown broadcast failed", exc_info=True)
        if self.watchdog is not None:
            # only AFTER the SHUTDOWN broadcast: the broadcast is itself a
            # collective, so returning from it means every follower has the
            # frame — stopping the responder earlier would let a long
            # in-flight step turn an orderly stop into follower probers
            # reading the leader as dead. The leader's own monitor can't
            # misfire meanwhile: followers keep pinging until they process
            # SHUTDOWN and stop their watchdogs themselves.
            self.watchdog.stop()
        if self._publisher is not None:
            self._publisher.clear()


def _validate_messages(messages: Any) -> List[Dict[str, Any]]:
    if not isinstance(messages, list) or not messages:
        raise ValueError("messages must be a non-empty list")
    for m in messages:
        if not isinstance(m, dict) or "role" not in m or "content" not in m:
            raise ValueError("each message needs role and content")
        if not isinstance(m["content"], str):
            # OpenAI content-parts arrays (multimodal) are not supported;
            # they would also crash HF chat templates with a 500
            raise ValueError("message content must be a string")
    return messages


def _lifecycle_usage(req: Any) -> Dict[str, Any]:
    """Per-request lifecycle extras for the OpenAI usage block — the
    engine-side measurements an open-loop load harness needs without
    streaming (`bench.py fleet` reads these): queue wait (submit ->
    first scheduled, the leg an actuation stall lands in) and decode
    TPOT (mean inter-token seconds after the first token)."""
    qw = None
    if req.first_sched_time is not None:
        qw = max(0.0, req.first_sched_time - req.submit_time)
    tpot = None
    n = len(req.out_tokens)
    if (
        req.first_token_time is not None
        and req.done_time is not None
        and n > 1
    ):
        tpot = max(0.0, (req.done_time - req.first_token_time) / (n - 1))
    out = {"queue_wait_s": qw, "decode_tpot_s": tpot}
    tid = getattr(req, "trace_id", "")
    if tid:
        # retained lifecycle trace (sampled or tail-kept): the handle a
        # client/harness passes to GET /v1/traces?trace_id=...
        out["trace_id"] = tid
    return out


def _finish_reason(service: "EngineService", req: Any) -> str:
    # the engine records why it finished (eos/stop-sequence vs budget);
    # fall back to the legacy eos check for requests that predate it
    if getattr(req, "finish_reason", ""):
        return req.finish_reason
    eos = service.engine.cfg.eos_token_id
    return (
        "stop" if req.out_tokens and req.out_tokens[-1] == eos else "length"
    )


class _CurrentTokenizer:
    """Tokenizer handle that always delegates to the service's *current*
    tokenizer, so handler closures built once at app construction follow
    model hot-swaps."""

    def __init__(self, service: EngineService) -> None:
        self._service = service

    def __getattr__(self, name: str):
        return getattr(self._service.tokenizer, name)


def build_app(service: EngineService) -> web.Application:
    app = web.Application()
    # read per-request, never captured: both change on a model hot-swap
    tok = _CurrentTokenizer(service)

    def _vocab() -> int:
        return service.engine.cfg.model.vocab_size

    def _encode_prompt(prompt: Any) -> List[int]:
        if isinstance(prompt, list):
            return [int(t) for t in prompt]
        if isinstance(prompt, str):
            return tok.encode(prompt)
        raise ValueError("prompt must be a string or a list of token ids")

    def _chat_tokens(messages: Any) -> List[int]:
        msgs = _validate_messages(messages)
        try:
            return tok.chat_tokens(msgs)
        except ValueError:
            raise
        except Exception as e:
            # jinja TemplateError on role patterns the model's template
            # rejects, TypeError on content-parts arrays, ...: malformed
            # request input, not a server fault -> 400
            raise ValueError(f"chat template failed: {e}")

    async def health(request: web.Request) -> web.Response:
        if service.failure is not None:
            return web.json_response(
                {"status": "FAILED", "error": service.failure}, status=503
            )
        if service.degraded is not None:
            # healed-in-process failures (rolled-back swap): still serving
            # — 200, so no controller restarts us — but visibly degraded
            # for operators and the launcher
            return web.json_response(
                {"status": "DEGRADED", "reason": service.degraded}
            )
        return web.json_response({"status": "OK"})

    async def is_sleeping(request: web.Request) -> web.Response:
        # `is_sleeping` is the reference wire contract; `devices_released`
        # is the TPU-specific extra the launcher's chip-exclusivity probe
        # needs (sleeping-but-client-open still holds the chip).
        return web.json_response(
            {
                "is_sleeping": service.sleeper.is_sleeping,
                "devices_released": service.sleeper.devices_released,
            }
        )

    def _traced_call(request: web.Request, fn):
        """Blocking admin call on the executor, with the caller's remote
        ``traceparent`` (if any) as the current context inside it."""
        return tracing.run_traced(
            asyncio.get_running_loop(), request.headers, fn
        )

    async def sleep(request: web.Request) -> web.Response:
        level = int(request.query.get("level", "1"))
        try:
            info = await _traced_call(request, lambda: service.sleep(level))
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e))
        return web.json_response(info)

    async def wake_up(request: web.Request) -> web.Response:
        info = await _traced_call(request, service.wake_up)
        return web.json_response(info)

    async def swap(request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except Exception:
            raise web.HTTPBadRequest(text="invalid JSON body")
        model = body.get("model")
        if not isinstance(model, str) or not model:
            raise web.HTTPBadRequest(text="swap requires a 'model' string")
        ckpt = body.get("checkpoint_dir") or ""
        if not isinstance(ckpt, str):
            raise web.HTTPBadRequest(text="checkpoint_dir must be a string")
        rid = body.get("request_id") or ""
        if not isinstance(rid, str):
            raise web.HTTPBadRequest(text="request_id must be a string")
        try:
            info = await _traced_call(
                request, lambda: service.swap(model, ckpt, request_id=rid)
            )
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e))
        except SwapRolledBack as e:
            # transactional rollback: the previous model serves again and
            # the target is still pooled — retryable, so 503 (not 500)
            return web.json_response(
                {
                    "error": str(e),
                    "rolled_back": True,
                    "model": service.args.model,
                },
                status=503,
            )
        return web.json_response(info)

    async def last_swap(request: web.Request) -> web.Response:
        # the launcher's timeout-recovery read: last committed swap (with
        # its request_id) + the degraded marker
        return web.json_response(
            {**service.last_swap, "degraded": service.degraded}
        )

    async def prefetch(request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except Exception:
            raise web.HTTPBadRequest(text="invalid JSON body")
        model = body.get("model")
        if not isinstance(model, str) or not model:
            raise web.HTTPBadRequest(text="prefetch requires a 'model' string")
        ckpt = body.get("checkpoint_dir") or ""
        if not isinstance(ckpt, str):
            raise web.HTTPBadRequest(text="checkpoint_dir must be a string")
        try:
            info = await _traced_call(
                request, lambda: service.prefetch(model, ckpt)
            )
        except (ValueError, FileNotFoundError) as e:
            raise web.HTTPBadRequest(text=str(e))
        return web.json_response(info)

    async def prefetch_status(request: web.Request) -> web.Response:
        return web.json_response(service.prefetch_status())

    async def prefetch_abort(request: web.Request) -> web.Response:
        info = await asyncio.get_running_loop().run_in_executor(
            None, service.abort_prefetch
        )
        return web.json_response(info)

    async def residents_get(request: web.Request) -> web.Response:
        return web.json_response(service.residents_view())

    async def residents_post(request: web.Request) -> web.Response:
        """POST /v1/residents: attach a sibling variant as co-resident
        (docs/engine.md "/v1/residents"). Admission rejection (cap /
        HBM budget / unresolvable source) is a 409: the caller falls
        back to the swap path."""
        try:
            body = await request.json()
        except Exception:
            raise web.HTTPBadRequest(text="invalid JSON body")
        model = body.get("model")
        if not isinstance(model, str) or not model:
            raise web.HTTPBadRequest(
                text="residents requires a 'model' string"
            )
        ckpt = body.get("checkpoint_dir") or ""
        if not isinstance(ckpt, str):
            raise web.HTTPBadRequest(text="checkpoint_dir must be a string")
        try:
            info = await _traced_call(
                request, lambda: service.attach_resident(model, ckpt)
            )
        except ResidentRejected as e:
            raise web.HTTPConflict(text=str(e))
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e))
        return web.json_response(info)

    async def residents_delete(request: web.Request) -> web.Response:
        """DELETE /v1/residents: detach a co-resident variant. 409 while
        the variant still has live or queued requests (drain first)."""
        model = request.query.get("model", "")
        ckpt = request.query.get("checkpoint_dir", "")
        if not model and request.can_read_body:
            try:
                body = await request.json()
            except Exception:
                raise web.HTTPBadRequest(text="invalid JSON body")
            model = body.get("model") or ""
            ckpt = body.get("checkpoint_dir") or ""
        if not isinstance(model, str) or not model:
            raise web.HTTPBadRequest(
                text="detach requires a 'model' (query or body)"
            )
        if not isinstance(ckpt, str):
            raise web.HTTPBadRequest(text="checkpoint_dir must be a string")
        try:
            info = await _traced_call(
                request, lambda: service.detach_resident(model, ckpt)
            )
        except ResidentRejected as e:
            raise web.HTTPConflict(text=str(e))
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e))
        return web.json_response(info)

    async def models(request: web.Request) -> web.Response:
        # the base plus every attached co-resident: what a router may
        # address in a completions body's "model" without an actuation
        data = [{"id": service.args.model, "object": "model"}]
        data += [
            {"id": m, "object": "model", "coresident": True}
            for m in sorted(service._residents)
        ]
        return web.json_response({"object": "list", "data": data})

    async def engine_stats(request: web.Request) -> web.Response:
        """JSON lifecycle stats (GET /v1/stats): the launcher's fleet
        rollup polls this instead of scraping+parsing /metrics."""
        return web.json_response(service.stats())

    async def costs_get(request: web.Request) -> web.Response:
        """GET /v1/costs: every candidate actuation priced before any
        byte moves (docs/operations.md "Pricing an actuation").
        ``?model=X[&checkpoint_dir=D]`` adds an arbitrary target to the
        candidate list. Pricing flattens weight trees, so it runs on the
        executor, never the event loop."""
        extras = []
        model = request.query.get("model", "")
        if model:
            extras.append(
                (model, request.query.get("checkpoint_dir", "") or "")
            )
        try:
            out = await asyncio.get_running_loop().run_in_executor(
                None, lambda: service.costs_view(extras)
            )
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e))
        return web.json_response(out)

    async def actuations_get(request: web.Request) -> web.Response:
        """GET /v1/actuations: the decision flight recorder's ring —
        ``?n=`` bounds the returned records (newest kept), ``?kind=``
        filters by actuation kind."""
        try:
            n = int(request.query.get("n", "0") or 0)
        except ValueError:
            raise web.HTTPBadRequest(text="n must be an integer")
        kind = request.query.get("kind") or None
        return web.json_response(service.actuations_view(n=n, kind=kind))

    async def metrics(request: web.Request) -> web.Response:
        from prometheus_client import generate_latest

        ENGINE_QUEUE_DEPTH.labels(model=service.args.model).set(
            service.queue_depth()
        )
        with service._slo_mu:
            # decayed to scrape time: after traffic stops the demand
            # signal visibly ramps down instead of freezing
            ENGINE_ARRIVAL_RATE.labels(model=service.args.model).set(
                service._arrival.rate(time.monotonic())
            )
        if service.engine.prefix_cache is not None:
            ENGINE_PREFIX_HIT_TOKENS.labels(model=service.args.model).set(
                service.engine.prefix_cache.hit_tokens
            )
        if service.engine.cfg.speculative_ngram > 0:
            ENGINE_SPEC_PROPOSED.labels(model=service.args.model).set(
                service.engine.spec_proposed
            )
            ENGINE_SPEC_ACCEPTED.labels(model=service.args.model).set(
                service.engine.spec_accepted
            )
        pool = service.model_pool
        ENGINE_POOL_BYTES.set(pool.bytes_used)
        ENGINE_POOL_MODELS.set(len(pool))
        if pool.chunks is not None:
            # running counters — the scrape never re-sums entries
            ENGINE_POOL_TIER_BYTES.labels(tier="host").set(
                pool.chunks.host_bytes
            )
            ENGINE_POOL_TIER_BYTES.labels(tier="disk").set(
                pool.chunks.disk_bytes
            )
            cd = pool.chunks.describe()
            ENGINE_POOL_TIER_CHUNKS.labels(tier="host").set(
                cd["host_chunks"]
            )
            ENGINE_POOL_TIER_CHUNKS.labels(tier="disk").set(
                cd["disk_chunks"]
            )
            ENGINE_POOL_DEDUP_SAVED.set(pool.chunks.dedup_saved_bytes)
        ENGINE_EXEC_POOL_BYTES.set(service.exec_pool.bytes_used)
        ENGINE_EXEC_POOL_ENTRIES.set(len(service.exec_pool))
        return web.Response(
            body=generate_latest(),
            content_type="text/plain",
        )

    def _parse_stop(stop: Any) -> tuple:
        """OpenAI `stop`: a string, a list of strings, or token-id lists.
        Malformed values must surface as ValueError (-> HTTP 400).

        Returns (token_seqs, stop_texts). Token-id stops match in the
        engine; STRING stops match on decoded text in the response layer
        (tokenizer.TextStopStream / truncate_at_text_stop) — BPE does not
        round-trip decode→encode, and a stop string can start mid-token,
        so re-encoding strings into token sequences would miss matches."""
        if stop is None:
            return (), ()
        vocab = _vocab()
        if isinstance(stop, str):
            stop = [stop]
        if not isinstance(stop, list):
            raise ValueError("stop must be a string or a list")
        seqs = []
        texts = []
        for s in stop:
            if isinstance(s, str):
                texts.append(s)
            elif isinstance(s, int):
                seqs.append((s % vocab,))
            elif isinstance(s, list):
                try:
                    seqs.append(tuple(int(t) % vocab for t in s))
                except (TypeError, ValueError) as e:
                    raise ValueError(f"invalid stop token list {s!r}") from e
            else:
                raise ValueError(f"invalid stop entry {s!r}")
        return tuple(s for s in seqs if s), tuple(t for t in texts if t)

    def _parse_generation(body: Dict[str, Any], tokens: List[int]):
        vocab = _vocab()
        tokens = [t % vocab for t in tokens]
        if not tokens:
            raise ValueError("empty prompt")
        try:
            mt = body.get("max_tokens")
            max_tokens = 16 if mt is None else int(mt)
            tv = body.get("temperature")
            temperature = 0.0 if tv is None else float(tv)
            top_p = float(
                1.0 if body.get("top_p") is None else body.get("top_p")
            )
        except (TypeError, ValueError) as e:
            raise ValueError(f"invalid generation parameter: {e}")
        if max_tokens < 1:
            raise ValueError(f"max_tokens must be >= 1, got {max_tokens}")
        from .engine import validate_logit_bias

        logit_bias = validate_logit_bias(body.get("logit_bias"), vocab)
        iev = body.get("ignore_eos")
        if iev is not None and not isinstance(iev, bool):
            raise ValueError(f"ignore_eos must be a bool, got {iev!r}")
        ignore_eos = bool(iev)
        sv = body.get("seed")
        if sv is not None and (isinstance(sv, bool) or not isinstance(sv, int)):
            raise ValueError(f"seed must be an integer, got {sv!r}")
        if sv is not None and not (-(2**63) <= sv < 2**63):
            # out-of-int64 seeds would overflow jax.random.key at
            # admission — inside the engine thread, not this request
            raise ValueError("seed must fit in a signed 64-bit integer")
        seed = None if sv is None else int(sv)
        if not (0.0 < top_p <= 1.0):
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        try:
            pp = body.get("presence_penalty")
            presence = 0.0 if pp is None else float(pp)
            fp = body.get("frequency_penalty")
            frequency = 0.0 if fp is None else float(fp)
        except (TypeError, ValueError) as e:
            raise ValueError(f"invalid penalty: {e}")
        for name, v in (("presence_penalty", presence), ("frequency_penalty", frequency)):
            if not (-2.0 <= v <= 2.0):
                raise ValueError(f"{name} must be in [-2, 2], got {v}")
        stop_seqs, stop_texts = _parse_stop(body.get("stop"))
        sti = body.get("stop_token_ids")
        if sti is not None:
            # vLLM's parameter name; matching is engine-level single-id
            # stops with OUR strip semantics (the matched token is removed
            # from the output, like every other stop here — vLLM keeps
            # non-special ids in the completion; docs/engine.md says so)
            if not isinstance(sti, list):
                raise ValueError("stop_token_ids must be a list of ints")
            extra = []
            for t in sti:
                if isinstance(t, bool) or not isinstance(t, int):
                    raise ValueError(
                        f"stop_token_ids entries must be ints, got {t!r}"
                    )
                if not (0 <= t < vocab):
                    # an id the model cannot emit: wrapping it onto an
                    # unrelated real token would truncate generations
                    # at random; reject instead
                    raise ValueError(
                        f"stop_token_ids entry {t} outside vocab [0, {vocab})"
                    )
                extra.append((t,))
            stop_seqs = stop_seqs + tuple(extra)
        # pre-validate everything add_request would reject, so streaming
        # requests fail with a 400 instead of an SSE error after headers
        # are out
        cfg = service.engine.cfg
        if len(tokens) + max_tokens > cfg.seq_len:
            raise ValueError(
                f"prompt+generation {len(tokens)}+{max_tokens} exceeds "
                f"max_seq_len {cfg.seq_len}"
            )
        from .kv_cache import PageAllocator

        need = PageAllocator.pages_needed(
            len(tokens) + max_tokens, cfg.page_size
        )
        if need > cfg.num_pages - 1:
            raise ValueError(
                f"request needs {need} pages but the pool only has "
                f"{cfg.num_pages - 1}"
            )
        return (
            tokens, max_tokens, temperature, top_p, stop_seqs, stop_texts,
            presence, frequency, seed, ignore_eos, logit_bias,
        )

    async def _stream_sse(
        request: web.Request,
        tokens: List[int],
        max_tokens: int,
        temperature: float,
        top_p: float,
        stop_seqs: tuple,
        stop_texts: tuple,
        presence: float,
        frequency: float,
        make_chunk,
        seed=None,
        ignore_eos=False,
        logit_bias=None,
        variant=0,
        trace_ctx=None,
        usage_chunk=None,
    ) -> web.StreamResponse:
        """OpenAI-style SSE stream: one `data: {json}` event per emitted
        token, `data: [DONE]` terminator. Tokens cross the engine-thread ->
        event-loop boundary via call_soon_threadsafe into an asyncio queue,
        so delivery granularity is the engine's decode chunk.

        Chunk text comes from an incremental detokenizer; stop STRINGS are
        matched here on the decoded text (held back until disambiguated)
        and end the stream early, aborting the in-flight generation.

        When the stream completes normally a final ``usage_chunk`` event
        precedes ``[DONE]``, carrying the lifecycle fields non-streaming
        responses already expose (queue_wait_s / decode_tpot_s /
        trace_id) — streamed requests are scoreable by the fleet harness
        too."""
        from .tokenizer import IncrementalDecoder, TextStopStream

        filt = TextStopStream(tok, stop_texts) if stop_texts else None
        dec = IncrementalDecoder(tok)
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()

        def on_token(req, tok: int) -> None:
            loop.call_soon_threadsafe(q.put_nowait, (tok, req.done))

        fut = service.submit(
            tokens, max_tokens, temperature, on_token=on_token,
            top_p=top_p, stop_seqs=stop_seqs,
            presence_penalty=presence, frequency_penalty=frequency,
            seed=seed, ignore_eos=ignore_eos, logit_bias=logit_bias,
            variant=variant, trace_ctx=trace_ctx,
        )
        afut = asyncio.ensure_future(asyncio.wrap_future(fut))
        resp = web.StreamResponse(
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
            }
        )
        qtask: Optional[asyncio.Task] = None
        completed = False
        try:
            # inside the try: a disconnect cancelling this await must still
            # abort the in-flight generation
            await resp.prepare(request)
            index = 0
            while True:
                if qtask is None:
                    qtask = asyncio.ensure_future(q.get())
                done_set, _ = await asyncio.wait(
                    {qtask, afut}, return_when=asyncio.FIRST_COMPLETED
                )
                if qtask in done_set:
                    t, req_done = qtask.result()
                    qtask = None
                    if filt is not None:
                        # the filter tracks id<->text attribution through
                        # its hold-back window: every emission's ids are
                        # exactly the tokens whose decoded text it contains
                        text, ids, matched = filt.push(t)
                        if not matched and req_done:
                            tail, tids, matched = filt.flush()
                            text += tail
                            ids = ids + tids
                        if matched:
                            # everything before the stop flushes in one
                            # final chunk; text AND ids of the (possibly
                            # partial) stop content are suppressed together
                            if text:
                                payload = json.dumps(
                                    make_chunk(text, ids, index)
                                )
                                index += 1
                                await resp.write(
                                    f"data: {payload}\n\n".encode()
                                )
                            if not req_done:
                                service.abort(fut)
                            completed = req_done
                            break
                        if not text and not req_done:
                            continue  # held back: ids stay in the filter
                    else:
                        text = dec.push(t)
                        if req_done:
                            text += dec.flush()
                        ids = [t]
                    payload = json.dumps(make_chunk(text, ids, index))
                    index += 1
                    await resp.write(f"data: {payload}\n\n".encode())
                    if req_done:
                        completed = True
                        break
                elif afut.done():
                    # finished without a terminal token event: submit error,
                    # engine failure, or an abort — surface it as an SSE
                    # error event (headers are already gone)
                    exc = (
                        afut.exception()
                        if not afut.cancelled()
                        else RuntimeError("request aborted")
                    )
                    if exc is not None:
                        err = json.dumps({"error": str(exc)})
                        await resp.write(f"data: {err}\n\n".encode())
                    break
            if completed and usage_chunk is not None:
                # the future resolves right after the terminal token (the
                # engine loop resolves it in the same step); shield keeps
                # the finally's cancel from killing a racing completion
                req = None
                with contextlib.suppress(Exception):
                    req = await asyncio.wait_for(
                        asyncio.shield(afut), timeout=5.0
                    )
                if req is not None and getattr(req, "error", None) is None:
                    u = {
                        "prompt_tokens": len(req.prompt),
                        "completion_tokens": len(req.out_tokens),
                        "time_to_first_token_s": (
                            (req.first_token_time - req.submit_time)
                            if req.first_token_time
                            else None
                        ),
                        **_lifecycle_usage(req),
                    }
                    payload = json.dumps(usage_chunk(u))
                    await resp.write(f"data: {payload}\n\n".encode())
            await resp.write(b"data: [DONE]\n\n")
        except (asyncio.CancelledError, ConnectionResetError):
            service.abort(fut)
            raise
        finally:
            if qtask is not None:
                qtask.cancel()
            afut.cancel()
        await resp.write_eof()
        return resp

    async def _await_generation(fut):
        try:
            return await asyncio.wrap_future(fut)
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e))
        except asyncio.CancelledError:
            # client disconnected: free the slot instead of decoding on
            service.abort(fut)
            raise

    def _parse_n(body: Dict[str, Any]) -> int:
        try:
            nv = body.get("n")
            n = 1 if nv is None else int(nv)
        except (TypeError, ValueError):
            raise web.HTTPBadRequest(text="n must be an integer")
        if not (1 <= n <= service.engine.cfg.max_batch):
            raise web.HTTPBadRequest(
                text=f"n must be in 1..{service.engine.cfg.max_batch}"
            )
        if body.get("stream") and n != 1:
            raise web.HTTPBadRequest(text="n > 1 is not supported with stream")
        return n

    def _top_dict(alts, n: int) -> Dict[str, float]:
        """OpenAI completions top_logprobs entry: decoded-token -> logprob.
        Distinct ids can decode to the same string (byte fallback,
        whitespace variants); keep the best logprob on collision."""
        out: Dict[str, float] = {}
        for tid, lp_ in alts[:n]:
            key = tok.decode([tid], skip_special=False)
            if key not in out or lp_ > out[key]:
                out[key] = lp_
        return out

    def _parse_logprobs_n(v: Any, field: str = "logprobs") -> int:
        """OpenAI completions `logprobs` / chat `top_logprobs`: false/true
        (sampled-token logprobs only) or an int = how many top
        alternatives per position. Bounded by the engine's compiled
        top-k. Validated BEFORE submission: a bad value must 400 without
        burning a full generation."""
        if v is None or isinstance(v, bool):
            return 0
        try:
            n = int(v)
        except (TypeError, ValueError):
            raise ValueError(f"{field} must be a bool or int, got {v!r}")
        limit = service.engine.cfg.logprobs_topk
        if n < 0 or n > limit:
            raise ValueError(
                f"{field} must be in [0, {limit}] (engine --logprobs-topk)"
            )
        return n

    def _text_stop_watcher(stop_texts: tuple):
        """Engine-thread callback that asks for early termination once the
        decoded text contains a stop string — without it, a non-streaming
        request with stops would decode to eos/max_tokens holding a batch
        slot, and only the response text would be truncated."""
        from .tokenizer import TextStopStream

        filt = TextStopStream(tok, stop_texts)

        def on_token(req, t: int) -> None:
            _, _, matched = filt.push(t)
            if matched:
                req.stop_requested = True

        return on_token

    async def _gather_n(
        n: int, tokens, max_tokens, temperature, top_p, stop_seqs,
        presence, frequency, stop_texts=(), want_alts=False,
        want_prompt_logprobs=False, seed=None, ignore_eos=False,
        logit_bias=None, variant=0, trace_ctx=None,
    ):
        """n parallel submissions; abort every sibling if any fails or the
        client goes away (no orphan decode cycles). Prefix caching makes
        the 2nd..nth prompt prefill nearly free (the OpenAI `n` param)."""
        futs = [
            service.submit(
                tokens, max_tokens, temperature,
                top_p=top_p, stop_seqs=stop_seqs,
                presence_penalty=presence, frequency_penalty=frequency,
                on_token=(
                    _text_stop_watcher(stop_texts) if stop_texts else None
                ),
                want_top_logprobs=want_alts,
                # prompt scores are identical across siblings: only the
                # first bypasses the prefix cache and pays the forward;
                # the response copies them onto the other choices
                want_prompt_logprobs=want_prompt_logprobs and i == 0,
                # OpenAI n + seed: distinct samples per choice, but the
                # SET of choices is reproducible. Wrap into int64 so a
                # seed near the bound that _parse_generation accepted
                # can't overflow jax.random.key for i>0.
                seed=None if seed is None
                else ((seed + i + 2**63) % 2**64) - 2**63,
                ignore_eos=ignore_eos,
                logit_bias=logit_bias,
                variant=variant,
                # the client's traceparent traces choice 0 (whose usage
                # the response carries); siblings stay on the sampler
                trace_ctx=trace_ctx if i == 0 else None,
            )
            for i in range(n)
        ]
        try:
            return [await _await_generation(f) for f in futs]
        except BaseException:
            for f in futs:
                if not f.done():
                    service.abort(f)
            raise

    async def completions(request: web.Request) -> web.StreamResponse:
        try:
            body = await request.json()
        except Exception:
            raise web.HTTPBadRequest(text="invalid JSON body")
        try:
            (
                tokens, max_tokens, temperature, top_p, stop_seqs,
                stop_texts, presence, frequency, seed, ignore_eos,
                logit_bias,
            ) = _parse_generation(body, _encode_prompt(body.get("prompt")))
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e))
        raw_prompt = body.get("prompt")
        # per-request model routing (docs/engine.md "/v1/residents"):
        # the body's "model" resolves to a co-resident variant handle;
        # unknown names 400 with the live set, so a router never
        # silently serves the wrong weights
        try:
            variant = service.resolve_request_model(body.get("model"))
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e))
        resp_model = body.get("model") or service.args.model
        trace_ctx = tracing.context_from_headers(request.headers)

        n = _parse_n(body)
        try:
            logprobs_n = _parse_logprobs_n(body.get("logprobs"), "logprobs")
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e))
        echo = bool(body.get("echo"))
        if body.get("stream"):
            if logprobs_n > 0:
                raise web.HTTPBadRequest(
                    text="integer logprobs is not supported with stream"
                )
            if echo:
                raise web.HTTPBadRequest(
                    text="echo is not supported with stream"
                )

            def chunk(text: str, ids: List[int], index: int) -> Dict[str, Any]:
                return {
                    "object": "text_completion",
                    "model": resp_model,
                    "choices": [
                        {"index": 0, "text": text, "token_ids": ids}
                    ],
                }

            def usage_chunk(usage: Dict[str, Any]) -> Dict[str, Any]:
                return {
                    "object": "text_completion",
                    "model": resp_model,
                    "choices": [],
                    "usage": usage,
                }

            return await _stream_sse(
                request, tokens, max_tokens, temperature, top_p, stop_seqs,
                stop_texts, presence, frequency, chunk, seed=seed,
                ignore_eos=ignore_eos, logit_bias=logit_bias,
                variant=variant, trace_ctx=trace_ctx,
                usage_chunk=usage_chunk,
            )

        reqs = await _gather_n(
            n, tokens, max_tokens, temperature, top_p, stop_seqs,
            presence, frequency, stop_texts, want_alts=logprobs_n > 0,
            want_prompt_logprobs=echo and bool(body.get("logprobs")),
            seed=seed, ignore_eos=ignore_eos, logit_bias=logit_bias,
            variant=variant, trace_ctx=trace_ctx,
        )
        req = reqs[0]
        ttft = (
            (req.first_token_time - req.submit_time)
            if req.first_token_time
            else None
        )
        from .tokenizer import truncate_at_text_stop

        choices = []
        total_completion = 0
        for i, r in enumerate(reqs):
            kept, kept_lps, text, matched = truncate_at_text_stop(
                tok, r.out_tokens, r.out_logprobs, stop_texts
            )
            total_completion += len(kept)
            choice = {
                "index": i,
                "token_ids": kept,
                "text": (
                    # echo returns the prompt the client sent: a text
                    # prompt verbatim (re-decoding would render the
                    # tokenizer's auto-added BOS), a token-id prompt as
                    # its literal decode, specials included (distinct
                    # special ids must not silently vanish)
                    (
                        raw_prompt
                        if isinstance(raw_prompt, str)
                        else tok.decode(tokens, skip_special=False)
                    )
                    + text
                    if echo
                    else text
                ),
                "finish_reason": (
                    "stop" if matched else _finish_reason(service, r)
                ),
            }
            if body.get("logprobs"):
                # OpenAI echo+logprobs: the arrays cover prompt tokens
                # too (first entry null — nothing precedes it)
                lp_tokens = (tokens + kept) if echo else kept
                lp_vals = (
                    (reqs[0].prompt_logprobs + kept_lps)
                    if echo
                    else kept_lps
                )
                choice["logprobs"] = {
                    "tokens": lp_tokens,
                    "token_logprobs": lp_vals,
                }
                if logprobs_n > 0:
                    tops = [
                        _top_dict(alts, logprobs_n)
                        for alts in r.out_top_logprobs[: len(kept)]
                    ]
                    if echo:
                        tops = [{} for _ in tokens] + tops
                    choice["logprobs"]["top_logprobs"] = tops
            choices.append(choice)
        return web.json_response(
            {
                "object": "text_completion",
                "model": resp_model,
                "choices": choices,
                "usage": {
                    "prompt_tokens": len(tokens),
                    "completion_tokens": total_completion,
                    "time_to_first_token_s": ttft,
                    **_lifecycle_usage(req),
                },
            }
        )

    async def chat_completions(request: web.Request) -> web.StreamResponse:
        try:
            body = await request.json()
        except Exception:
            raise web.HTTPBadRequest(text="invalid JSON body")
        try:
            (
                tokens, max_tokens, temperature, top_p, stop_seqs,
                stop_texts, presence, frequency, seed, ignore_eos,
                logit_bias,
            ) = _parse_generation(body, _chat_tokens(body.get("messages")))
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e))
        try:
            variant = service.resolve_request_model(body.get("model"))
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e))
        resp_model = body.get("model") or service.args.model
        trace_ctx = tracing.context_from_headers(request.headers)
        n = _parse_n(body)
        try:
            top_n = (
                _parse_logprobs_n(body.get("top_logprobs"), "top_logprobs")
                if body.get("logprobs")
                else 0
            )
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e))
        if body.get("stream"):
            if top_n > 0:
                raise web.HTTPBadRequest(
                    text="top_logprobs is not supported with stream"
                )

            def chunk(text: str, ids: List[int], index: int) -> Dict[str, Any]:
                delta: Dict[str, Any] = {"content": text}
                if index == 0:
                    delta["role"] = "assistant"
                return {
                    "object": "chat.completion.chunk",
                    "model": resp_model,
                    "choices": [{"index": 0, "delta": delta}],
                }

            def usage_chunk(usage: Dict[str, Any]) -> Dict[str, Any]:
                return {
                    "object": "chat.completion.chunk",
                    "model": resp_model,
                    "choices": [],
                    "usage": usage,
                }

            return await _stream_sse(
                request, tokens, max_tokens, temperature, top_p, stop_seqs,
                stop_texts, presence, frequency, chunk, seed=seed,
                ignore_eos=ignore_eos, logit_bias=logit_bias,
                variant=variant, trace_ctx=trace_ctx,
                usage_chunk=usage_chunk,
            )

        reqs = await _gather_n(
            n, tokens, max_tokens, temperature, top_p, stop_seqs,
            presence, frequency, stop_texts, want_alts=top_n > 0, seed=seed,
            ignore_eos=ignore_eos, logit_bias=logit_bias, variant=variant,
            trace_ctx=trace_ctx,
        )
        from .tokenizer import truncate_at_text_stop

        choices = []
        total_completion = 0
        for i, r in enumerate(reqs):
            kept, kept_lps, text, matched = truncate_at_text_stop(
                tok, r.out_tokens, r.out_logprobs, stop_texts
            )
            total_completion += len(kept)
            choice = {
                "index": i,
                "message": {
                    "role": "assistant",
                    "content": text,
                    "token_ids": kept,
                },
                "finish_reason": (
                    "stop" if matched else _finish_reason(service, r)
                ),
            }
            if body.get("logprobs"):
                # OpenAI chat logprobs shape: per-token entries with
                # optional top_logprobs alternatives
                choice["logprobs"] = {
                    "content": [
                        {
                            "token": tok.decode([tid], skip_special=False),
                            "logprob": lp,
                            "top_logprobs": [
                                {
                                    "token": tok.decode(
                                        [aid], skip_special=False
                                    ),
                                    "logprob": alp,
                                }
                                for aid, alp in alts[:top_n]
                            ],
                        }
                        for tid, lp, alts in zip(
                            kept, kept_lps, r.out_top_logprobs[: len(kept)]
                        )
                    ]
                }
            choices.append(choice)
        return web.json_response(
            {
                "object": "chat.completion",
                "model": resp_model,
                "choices": choices,
                "usage": {
                    "prompt_tokens": len(tokens),
                    "completion_tokens": total_completion,
                    **_lifecycle_usage(reqs[0]),
                },
            }
        )

    app.router.add_get("/health", health)
    app.router.add_get("/is_sleeping", is_sleeping)
    app.router.add_post("/sleep", sleep)
    app.router.add_post("/wake_up", wake_up)
    async def faults_get(request: web.Request) -> web.Response:
        return web.json_response(faults.describe())

    async def faults_arm(request: web.Request) -> web.Response:
        """Arm fault-injection points at runtime (the test / fault-drill
        surface; utils/faults.py): {"spec": "swap.h2d=fail:1,..."}."""
        try:
            body = await request.json()
        except Exception:
            raise web.HTTPBadRequest(text="invalid JSON body")
        spec = body.get("spec")
        if not isinstance(spec, str) or not spec:
            raise web.HTTPBadRequest(text="faults requires a 'spec' string")
        try:
            faults.arm_spec(spec)
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e))
        return web.json_response(faults.describe())

    async def faults_reset(request: web.Request) -> web.Response:
        faults.reset()
        return web.json_response(faults.describe())

    async def parked_export(request: web.Request) -> web.Response:
        """GET /v1/parked/{model}: park every live stream and export the
        bundle wire document (docs/engine.md "/v1/parked"). 409 when a
        precondition refuses with nothing displaced; 500 when the export
        leg failed AFTER the park — the streams already resumed locally."""
        model = request.match_info["model"]
        try:
            info = await _traced_call(
                request, lambda: service.export_parked(model)
            )
        except MigrationRejected as e:
            raise web.HTTPConflict(text=str(e))
        except MigrationFailed as e:
            raise web.HTTPInternalServerError(text=str(e))
        return web.json_response(info)

    async def parked_import(request: web.Request) -> web.Response:
        """POST /v1/parked: seat an exported bundle. 400 on a corrupt
        document (wire version, KV chunk digests), 409 on identity or
        capacity refusal (destination untouched), 500 on a seat failure
        (destination rolled back clean) or the drilled lost-ack —
        retrying the SAME document is safe: the stored ack replays."""
        try:
            body = await request.json()
        except Exception:
            raise web.HTTPBadRequest(text="invalid JSON body")
        try:
            info = await _traced_call(
                request, lambda: service.import_parked(body)
            )
        except MigrationRejected as e:
            raise web.HTTPConflict(text=str(e))
        except MigrationFailed as e:
            raise web.HTTPInternalServerError(text=str(e))
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e))
        return web.json_response(info)

    async def parked_release(request: web.Request) -> web.Response:
        """POST /v1/parked/release: commit the handoff (import acked) —
        spends the fence; a second release, or a release after abort, is
        a 409 (double-resume refusal)."""
        try:
            body = await request.json()
        except Exception:
            raise web.HTTPBadRequest(text="invalid JSON body")
        token = body.get("fence_token")
        if not isinstance(token, str) or not token:
            raise web.HTTPBadRequest(
                text="release requires a 'fence_token' string"
            )
        dest = body.get("dest") or ""
        claims = body.get("claims") or {}
        if not isinstance(dest, str) or not isinstance(claims, dict):
            raise web.HTTPBadRequest(
                text="'dest' must be a string and 'claims' an object"
            )
        try:
            info = await _traced_call(
                request,
                lambda: service.release_parked(
                    token, dest=dest, claims=claims
                ),
            )
        except MigrationRejected as e:
            raise web.HTTPConflict(text=str(e))
        return web.json_response(info)

    async def parked_abort(request: web.Request) -> web.Response:
        """POST /v1/parked/abort: roll the handoff back (import failed /
        destination gone) — spends the fence and resumes the parked
        streams locally."""
        try:
            body = await request.json()
        except Exception:
            raise web.HTTPBadRequest(text="invalid JSON body")
        token = body.get("fence_token")
        if not isinstance(token, str) or not token:
            raise web.HTTPBadRequest(
                text="abort requires a 'fence_token' string"
            )
        try:
            info = await _traced_call(
                request, lambda: service.abort_migration(token)
            )
        except MigrationRejected as e:
            raise web.HTTPConflict(text=str(e))
        return web.json_response(info)

    async def parked_claim(request: web.Request) -> web.Response:
        """GET /v1/parked/claims/{claim_id}: one migrated-in stream's
        progress (long-poll with ?wait_s= and ?have=) — what the source's
        proxy watchers consume."""
        cid = request.match_info["claim_id"]
        try:
            wait_s = float(request.query.get("wait_s", "0"))
            have = int(request.query.get("have", "-1"))
        except ValueError:
            raise web.HTTPBadRequest(text="wait_s/have must be numeric")
        try:
            info = await asyncio.get_running_loop().run_in_executor(
                None,
                lambda: service.claim_view(cid, wait_s=wait_s, have=have),
            )
        except ValueError as e:
            raise web.HTTPNotFound(text=str(e))
        return web.json_response(info)

    async def parked_claim_abort(request: web.Request) -> web.Response:
        """DELETE /v1/parked/claims/{claim_id}: the source proxy's
        client dropped — abort the migrated-in stream on this
        (destination) instance too."""
        cid = request.match_info["claim_id"]
        try:
            info = await asyncio.get_running_loop().run_in_executor(
                None, lambda: service.abort_claim(cid)
            )
        except ValueError as e:
            raise web.HTTPNotFound(text=str(e))
        return web.json_response(info)

    async def traces(request: web.Request) -> web.Response:
        """Export this process's span ring buffer: Chrome trace-event JSON
        (Perfetto-loadable, the default) or ``?format=tree`` (human);
        ``?trace_id=`` filters to one trace, ``?clear=1`` drains after
        export (docs/tracing.md)."""
        status, body, ctype = tracing.export_http(
            request.query.get("format", "chrome"),
            trace_id=request.query.get("trace_id") or None,
            clear=request.query.get("clear") in ("1", "true"),
        )
        return web.Response(status=status, text=body, content_type=ctype)

    async def profile_start(request: web.Request) -> web.Response:
        log_dir = ""
        if request.can_read_body:
            try:
                body = await request.json()
            except Exception:
                raise web.HTTPBadRequest(text="invalid JSON body")
            log_dir = body.get("log_dir") or ""
            if not isinstance(log_dir, str):
                raise web.HTTPBadRequest(text="log_dir must be a string")
        try:
            info = await asyncio.get_running_loop().run_in_executor(
                None, lambda: service.start_profile(log_dir)
            )
        except ProfileConflict as e:
            raise web.HTTPConflict(text=str(e))
        except Exception as e:  # noqa: BLE001 — profiler backend failures
            raise web.HTTPInternalServerError(text=f"start_trace: {e}")
        return web.json_response(info)

    async def profile_stop(request: web.Request) -> web.Response:
        try:
            info = await asyncio.get_running_loop().run_in_executor(
                None, service.stop_profile
            )
        except ProfileConflict as e:
            raise web.HTTPConflict(text=str(e))
        except Exception as e:  # noqa: BLE001 — profiler backend failures
            raise web.HTTPInternalServerError(text=f"stop_trace: {e}")
        return web.json_response(info)

    async def profile_status(request: web.Request) -> web.Response:
        return web.json_response(service.profile_status())

    app.router.add_post("/v1/swap", swap)
    app.router.add_get("/v1/swap", last_swap)
    app.router.add_get("/v1/faults", faults_get)
    app.router.add_post("/v1/faults", faults_arm)
    app.router.add_delete("/v1/faults", faults_reset)
    app.router.add_post("/v1/prefetch", prefetch)
    app.router.add_get("/v1/prefetch", prefetch_status)
    app.router.add_delete("/v1/prefetch", prefetch_abort)
    app.router.add_get("/v1/residents", residents_get)
    app.router.add_post("/v1/residents", residents_post)
    app.router.add_delete("/v1/residents", residents_delete)
    app.router.add_post("/v1/parked", parked_import)
    app.router.add_post("/v1/parked/release", parked_release)
    app.router.add_post("/v1/parked/abort", parked_abort)
    app.router.add_get("/v1/parked/claims/{claim_id}", parked_claim)
    app.router.add_delete("/v1/parked/claims/{claim_id}", parked_claim_abort)
    app.router.add_get("/v1/parked/{model}", parked_export)
    app.router.add_get("/v1/traces", traces)
    app.router.add_post("/v1/profile", profile_start)
    app.router.add_delete("/v1/profile", profile_stop)
    app.router.add_get("/v1/profile", profile_status)
    app.router.add_get("/v1/models", models)
    app.router.add_get("/v1/stats", engine_stats)
    app.router.add_get("/v1/costs", costs_get)
    app.router.add_get("/v1/actuations", actuations_get)
    app.router.add_get("/metrics", metrics)
    app.router.add_post("/v1/completions", completions)
    app.router.add_post("/v1/chat/completions", chat_completions)

    if os.environ.get("FMA_DEBUG_ENDPOINTS") == "1":
        # test-server role (SURVEY §4): crash induction for the
        # stopped-instance-recovery e2e (the reference kills its test server
        # the same way; the sentinel must see a real process death)
        async def debug_crash(request: web.Request) -> web.Response:
            import threading

            threading.Timer(0.1, lambda: os._exit(17)).start()
            return web.json_response({"crashing": True})

        app.router.add_post("/debug/crash", debug_crash)
    return app


def run_server(args: argparse.Namespace) -> None:
    """Blocking server main (the child process body)."""
    logging.basicConfig(level=logging.INFO)
    service = EngineService(args)
    app = build_app(service)
    try:
        web.run_app(
            app, host=args.host, port=args.port, print=None, handle_signals=True
        )
    finally:
        service.shutdown()


def main(argv: Optional[List[str]] = None) -> None:
    args = make_arg_parser().parse_args(argv)
    validate_parsed_args(args)
    run_server(args)


if __name__ == "__main__":
    main()
