"""Content-addressed weight chunks: the substrate of the tiered model pool.

Real fleets serve dozens of fine-tune variants of one base model; a flat
per-model host pool stores the shared base tensors once PER VARIANT and a
swap between siblings moves the whole checkpoint over PCIe. This module
makes weight identity content-addressed instead of model-addressed:

  * every leaf staged by the loaders (models/hf.py, models/checkpoint.py)
    gets a sha256 **digest** computed exactly once at load time;
  * :class:`ChunkStore` holds host-resident weight chunks keyed by digest,
    **refcounted** so two pooled fine-tunes of one base hold their common
    tensors in host DRAM exactly once (the dedup the tiered pool reports
    as ``dedup_saved_bytes``);
  * chunks whose last reference drops **spill to a local-disk tier**
    (bounded LRU, atomic-rename writes, content-verified reload — a
    stale/corrupt/colliding blob is a miss, never wrong weights), so an
    evicted variant can be rebuilt from local SSD instead of re-reading
    its checkpoint over the network.

The same digests drive the **delta-aware hot-swap**
(engine/sleep.py:swap_states): leaves the incoming and outgoing models
share by content hash never cross the device boundary at all — the live
device array is handed over and only the delta moves.

Grounding: 10Cache's cost-aware tier placement/migration and "Memory
Offloading for LLM Inference with Latency SLO Guarantees" (PAPERS.md) —
tier residency decisions here are recency+refcount driven, with the disk
tier as the cheap slot below host DRAM.

Mirrors engine/exec_pool.py's spill discipline (bounded budget per tier,
atomic rename, stale-blob-is-a-miss) for weights instead of executables.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

#: disk chunk files: "<sha256-of-digest>.chunk" under the spill dir —
#: hashing the digest for the filename keeps names fixed-length and
#: filesystem-safe regardless of how the digest scheme evolves
_CHUNK_SUFFIX = ".chunk"


def default_disk_dir() -> str:
    """Where the weight-chunk disk tier lives when ``--pool-disk-dir`` is
    not given: ``FMA_POOL_SPILL_DIR`` (exported by deployments next to the
    compile cache), else disabled."""
    return os.environ.get("FMA_POOL_SPILL_DIR", "")


def leaf_digest(arr: Any) -> str:
    """Content digest of one weight leaf: sha256 over (dtype, shape, raw
    bytes). Computed ONCE at load/stage time; equality implies bit-equal
    arrays of identical shape+dtype, so a digest match is sufficient for
    the delta-swap's device-array reuse."""
    a = np.asarray(arr)
    h = hashlib.sha256()
    h.update(str(a.dtype).encode())
    h.update(b"|")
    h.update(",".join(str(d) for d in a.shape).encode())
    h.update(b"|")
    if not a.flags["C_CONTIGUOUS"]:
        a = np.ascontiguousarray(a)
    h.update(a.tobytes())
    return h.hexdigest()


def unflatten_tree(flat: Dict[str, Any]) -> Dict[str, Any]:
    """Nested dict from '/'-joined flat keys — the inverse of the flat-key
    convention every digest map and manifest in this module uses (one
    definition: models/hf.py and the pool's manifest reconstruction both
    delegate here)."""
    out: Dict[str, Any] = {}
    for key, v in flat.items():
        parts = key.split("/")
        node = out
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return out


def digest_tree(params: Dict[str, Any]) -> Dict[str, str]:
    """Flat-key -> digest over a nested host param tree (the loaders
    compute this incrementally instead; this is the offline/bench path)."""
    out: Dict[str, str] = {}

    def walk(node: Any, prefix: Tuple[str, ...]) -> None:
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, prefix + (k,))
        else:
            out["/".join(prefix)] = leaf_digest(node)

    walk(params, ())
    return out


def aligned_digests(
    state: Any, digests: Optional[Dict[str, str]], prefix: str = "params"
) -> List[Optional[str]]:
    """Per-leaf digest list aligned with ``jax.tree.flatten(state)`` order.

    ``digests`` maps flat param keys ("embed", "layers/wq", ...) to
    digests; leaves outside the ``prefix`` subtree (the KV pool, scheduler
    arrays) get None — they are never content-matched. This is the
    alignment contract between the service's digest bookkeeping and
    ``swap_states``'s leaf lists."""
    from jax.tree_util import tree_flatten_with_path

    flat, _ = tree_flatten_with_path(state)
    out: List[Optional[str]] = []
    for path, _leaf in flat:
        if not digests:
            out.append(None)
            continue
        keys = []
        for k in path:
            if hasattr(k, "key"):
                keys.append(str(k.key))
            elif hasattr(k, "idx"):
                keys.append(str(k.idx))
            else:  # pragma: no cover — exotic pytree key types
                keys.append(str(k))
        if prefix:
            if keys and keys[0] == prefix:
                out.append(digests.get("/".join(keys[1:])))
            else:
                out.append(None)
        else:
            out.append(digests.get("/".join(keys)))
    return out


#: digest prefix marking chunks whose digest is NOT the plain content
#: hash of their bytes (transfer-quantized payloads, models/quant.py
#: transfer_digest). Their spill blobs carry an explicit ``content``
#: field in the header — the payload's own :func:`leaf_digest`, written
#: by the process that held the genuine chunk — which the reload
#: re-verification checks instead of recomputing the (un-invertible)
#: transfer digest. Sound because transfer_digest's preimage includes
#: leaf_digest(payload): equal q: digests imply equal payload bytes.
QUANT_DIGEST_PREFIX = "q:"

#: digest prefix of MESH-qualified digests: ``m:<qual>:<content>`` where
#: ``qual`` hashes (mesh shape | per-leaf sharding spec) and ``content``
#: is the plain :func:`leaf_digest` of the full (global) host array. The
#: qualifier makes sharded weight identity shard-qualified — a tp=2
#: entry never content-matches (or is served the disk blob of) the same
#: bytes placed single-device or under another mesh shape — while the
#: content suffix keeps disk blobs re-verifiable on reload.
MESH_DIGEST_PREFIX = "m:"


def digest_spillable(digest: str) -> bool:
    """Every digest scheme spills now. Quant-tier (``q:``) chunks were
    historically pinned in host RAM because their digest can't be
    recomputed from the blob bytes; the spill header's ``content`` field
    restores a content-verified reload for them, so the pin is gone.
    Kept as a function so external callers gating on it keep working."""
    return True


def qualify_digest(content_digest: str, qualifier: str) -> str:
    """Shard-qualify a plain content digest for a mesh placement
    (``qualifier`` = "tp=<N>|<PartitionSpec str>" — parallel.mesh.
    flat_spec_strs). Collectively the result covers dtype | global shape
    | sharding spec | bytes. Idempotent: an already-qualified (or
    transfer-quantized ``q:``) digest passes through unchanged, so the
    tier/prefetch staging paths can re-qualify carried-through maps
    safely."""
    if content_digest.startswith(
        (MESH_DIGEST_PREFIX, QUANT_DIGEST_PREFIX)
    ):
        return content_digest
    qual = hashlib.sha256(qualifier.encode()).hexdigest()[:12]
    return f"{MESH_DIGEST_PREFIX}{qual}:{content_digest}"


def digest_content_hash(digest: str) -> str:
    """The plain content-hash part of a (possibly mesh-qualified)
    digest: what the disk tier's reload re-verification recomputes over
    the file bytes."""
    if digest.startswith(MESH_DIGEST_PREFIX):
        return digest.rsplit(":", 1)[-1]
    return digest


@dataclass
class _Chunk:
    digest: str
    data: np.ndarray
    nbytes: int
    refs: int = 0
    stored_at: float = field(default_factory=time.monotonic)


class ChunkStore:
    """Refcounted host tier + bounded disk tier of content-addressed chunks.

    Host tier: chunks live here exactly while referenced (refs > 0) by
    pool entries; ``intern`` dedupes (a second variant's identical tensor
    returns the FIRST one's array and adds a reference), ``release`` drops
    a reference and — when the last one goes — spills the chunk to the
    disk tier before freeing its host bytes.

    Disk tier: bounded LRU of spilled chunks (``disk_budget_bytes``;
    <= 0 or empty ``disk_dir`` disables it). Writes are atomic-rename;
    ``fetch`` re-verifies the content hash on reload, so a stale, torn,
    corrupt, or hash-colliding blob is a miss (the caller cold-loads),
    never silently wrong weights.

    All byte totals are RUNNING counters — O(1) reads from /metrics, no
    re-summing under the lock. ``on_event(kind)`` mirrors traffic into
    Prometheus without this module importing prometheus. Thread-safe.
    """

    def __init__(
        self,
        disk_dir: str = "",
        disk_budget_bytes: int = 0,
        on_event: Optional[Callable[[str], None]] = None,
    ) -> None:
        self.disk_dir = disk_dir or ""
        self.disk_budget_bytes = int(disk_budget_bytes)
        self._mu = threading.Lock()
        self._chunks: Dict[str, _Chunk] = {}
        #: digest -> file size; insertion order is the disk LRU order
        self._disk: "OrderedDict[str, int]" = OrderedDict()
        self._on_event = on_event or (lambda kind: None)
        # running counters (the O(n) re-sum fix, module docstring)
        self.host_bytes = 0
        self.disk_bytes = 0
        self.dedup_saved_bytes = 0
        # traffic counters
        self.dedup_hits = 0
        self.disk_spills = 0
        self.disk_hits = 0
        self.disk_evictions = 0
        self.verify_failures = 0
        if self._disk_enabled():
            self._scan_disk()

    def __len__(self) -> int:
        return len(self._chunks)

    def __contains__(self, digest: str) -> bool:
        return digest in self._chunks

    # -- host tier ------------------------------------------------------------

    def intern(self, digest: str, arr: np.ndarray) -> Tuple[np.ndarray, int]:
        """Register one reference to `digest`, using `arr` as its content
        when the chunk is new. Returns ``(canonical_array, added_bytes)``:
        on a dedup hit the canonical array is the EXISTING chunk's (the
        caller drops its duplicate — that is the host-DRAM saving) and
        added_bytes is 0."""
        with self._mu:
            c = self._chunks.get(digest)
            if c is not None:
                c.refs += 1
                self.dedup_hits += 1
                self.dedup_saved_bytes += c.nbytes
                self._on_event("dedup_hit")
                return c.data, 0
            nb = int(arr.nbytes)
            self._chunks[digest] = _Chunk(
                digest=digest, data=arr, nbytes=nb, refs=1
            )
            self.host_bytes += nb
            return arr, nb

    def release(self, digest: str, spill: bool = True) -> int:
        """Drop one reference; when the last goes, spill the chunk to the
        disk tier (``spill=True`` — the eviction path) and free its host
        bytes. Returns host bytes freed (0 while other references hold
        it)."""
        freed = self._drop_ref(digest)
        if freed is None:
            return 0
        data, nb = freed
        if spill and digest_spillable(digest):
            self._spill(digest, data)
        return nb

    def release_deferred(
        self, digest: str
    ) -> Optional[Tuple[str, np.ndarray]]:
        """Drop one reference WITHOUT spilling inline: when the last goes,
        returns ``(digest, data)`` for the caller to :meth:`spill` after
        dropping its own locks — the eviction loop runs under the pool
        mutex and must not do disk I/O there. None while still
        referenced."""
        freed = self._drop_ref(digest)
        if freed is None or not digest_spillable(digest):
            return None
        return digest, freed[0]

    def _drop_ref(
        self, digest: str
    ) -> Optional[Tuple[np.ndarray, int]]:
        with self._mu:
            c = self._chunks.get(digest)
            if c is None:
                return None
            if c.refs > 1:
                c.refs -= 1
                self.dedup_saved_bytes -= c.nbytes
                return None
            data, nb = c.data, c.nbytes
            del self._chunks[digest]
            self.host_bytes -= nb
        return data, nb

    def spill(self, digest: str, data: np.ndarray) -> bool:
        """Write one freed chunk to the disk tier (the deferred half of
        :meth:`release_deferred`)."""
        return self._spill(digest, data)

    def peek_tier(self, digest: str) -> Optional[str]:
        """Which tier could serve `digest` right now — ``"host"`` (a live
        refcounted chunk), ``"disk"`` (a spilled blob is registered; its
        content verify still happens at fetch time), or None (miss) —
        WITHOUT reading, verifying, or touching LRU order. The cost
        oracle's tier probe: pricing an actuation must never consume the
        state it prices (``GET /v1/costs``)."""
        with self._mu:
            if digest in self._chunks:
                return "host"
            if self._disk_enabled() and digest in self._disk:
                return "disk"
        return None

    def fetch(self, digest: str) -> Optional[np.ndarray]:
        """Resolve a digest: host tier first (zero-copy — the array a
        sibling variant still references), then a verified disk-tier
        reload; None = genuine miss (the caller cold-loads). Does NOT take
        a reference — callers that re-pool the result intern it again."""
        with self._mu:
            c = self._chunks.get(digest)
            if c is not None:
                self._on_event("host_hit")
                return c.data
        return self._load_spilled(digest)

    # -- disk tier ------------------------------------------------------------

    def _disk_enabled(self) -> bool:
        return bool(self.disk_dir) and self.disk_budget_bytes > 0

    def _path(self, digest: str) -> str:
        name = hashlib.sha256(digest.encode()).hexdigest() + _CHUNK_SUFFIX
        return os.path.join(self.disk_dir, name)

    def _scan_disk(self) -> None:
        """Adopt chunk files from prior runs (oldest-first = LRU order) so
        the disk tier survives an instance restart, trimming to budget."""
        try:
            entries = []
            for f in os.listdir(self.disk_dir):
                if not f.endswith(_CHUNK_SUFFIX):
                    continue
                p = os.path.join(self.disk_dir, f)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                entries.append((st.st_mtime, p, st.st_size))
            entries.sort()
            with self._mu:
                for _, p, size in entries:
                    digest = self._read_header_digest(p)
                    if digest is None:
                        continue
                    self._disk[digest] = size
                    self.disk_bytes += size
                self._trim_disk_locked()
        except OSError:
            pass

    @staticmethod
    def _read_header_digest(path: str) -> Optional[str]:
        try:
            with open(path, "rb") as f:
                header = f.readline(4096)
            return json.loads(header).get("digest")
        except Exception:  # noqa: BLE001 — a torn header is just not adopted
            return None

    def _spill(self, digest: str, data: np.ndarray) -> bool:
        if not self._disk_enabled() or not digest_spillable(digest):
            return False
        with self._mu:
            if digest in self._disk:
                # already on disk from an earlier cycle — still a fresh
                # use: touch the LRU so a hot, repeatedly-respilled chunk
                # (a shared base tensor) isn't evicted as stale
                self._disk.move_to_end(digest)
                return True
        header = json.dumps(
            {
                "digest": digest,
                "dtype": str(data.dtype),
                "shape": list(data.shape),
                "nbytes": int(data.nbytes),
                # payload's own content hash, written while we hold the
                # genuine chunk: the reload verify target for q: digests
                # (whose digest is not recomputable from the blob bytes)
                "content": leaf_digest(data),
            }
        ).encode()
        path = self._path(digest)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            os.makedirs(self.disk_dir, exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(header)
                f.write(b"\n")
                if not data.flags["C_CONTIGUOUS"]:
                    data = np.ascontiguousarray(data)
                f.write(data.tobytes())
            os.replace(tmp, path)  # atomic: readers never see a torn file
        except OSError:
            logger.warning("chunk spill failed for %s", digest[:16], exc_info=True)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        size = len(header) + 1 + int(data.nbytes)
        with self._mu:
            self._disk[digest] = size
            self._disk.move_to_end(digest)
            self.disk_bytes += size
            self.disk_spills += 1
            self._on_event("disk_spill")
            self._trim_disk_locked()
        return True

    def _trim_disk_locked(self) -> None:
        while self.disk_bytes > self.disk_budget_bytes and self._disk:
            victim, size = self._disk.popitem(last=False)
            self.disk_bytes -= size
            self.disk_evictions += 1
            self._on_event("disk_eviction")
            try:
                os.unlink(self._path(victim))
            except OSError:
                pass

    def _load_spilled(self, digest: str) -> Optional[np.ndarray]:
        if not self._disk_enabled():
            self._on_event("miss")
            return None
        path = self._path(digest)
        try:
            with open(path, "rb") as f:
                header = json.loads(f.readline())
                raw = f.read()
        except (OSError, ValueError):
            self._forget_disk(digest)
            self._on_event("miss")
            return None
        try:
            # CONTENT verify on every reload: a stale blob, bitrot, or an
            # (astronomically unlikely) collision must be a miss, never
            # silently-wrong weights. Plain and mesh-qualified digests
            # recompute the content hash the digest itself names (the
            # qualifier is part of the lookup key, already matched by
            # reaching this path). Transfer-quantized q: digests are not
            # recomputable from the blob bytes; they verify against the
            # header's ``content`` field, written at spill time by the
            # process holding the genuine chunk (sound because
            # transfer_digest's preimage includes leaf_digest(payload)).
            dtype = np.dtype(header["dtype"])
            arr = np.frombuffer(raw, dtype=dtype).reshape(header["shape"])
            if digest.startswith(QUANT_DIGEST_PREFIX):
                want = header.get("content")
                if (
                    header.get("digest") != digest
                    or not want
                    or leaf_digest(arr) != want
                ):
                    raise ValueError("content digest mismatch")
            elif (
                header.get("digest") != digest
                or leaf_digest(arr) != digest_content_hash(digest)
            ):
                raise ValueError("content digest mismatch")
        except Exception:  # noqa: BLE001 — any malformed blob is a miss
            with self._mu:
                self.verify_failures += 1
            self._on_event("verify_failure")
            self._forget_disk(digest)
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        with self._mu:
            if digest in self._disk:
                self._disk.move_to_end(digest)  # LRU touch
            self.disk_hits += 1
            self._on_event("disk_hit")
        return arr

    def _forget_disk(self, digest: str) -> None:
        with self._mu:
            size = self._disk.pop(digest, None)
            if size is not None:
                self.disk_bytes -= size

    # -- observability --------------------------------------------------------

    def describe(self) -> Dict[str, Any]:
        with self._mu:
            return {
                "host_chunks": len(self._chunks),
                "host_bytes": self.host_bytes,
                "dedup_saved_bytes": self.dedup_saved_bytes,
                "dedup_hits": self.dedup_hits,
                "disk_dir": self.disk_dir if self._disk_enabled() else "",
                "disk_budget_bytes": self.disk_budget_bytes,
                "disk_chunks": len(self._disk),
                "disk_bytes": self.disk_bytes,
                "disk_spills": self.disk_spills,
                "disk_hits": self.disk_hits,
                "disk_evictions": self.disk_evictions,
                "verify_failures": self.verify_failures,
            }
