"""Sleep/wake: move live model state HBM <-> host without killing the
process — optionally releasing the TPU itself.

The reference's headline capability (vLLM sleep mode: ~3 s wake for 64 GiB,
README.md:16-26), rebuilt on XLA memory kinds: every array keeps its sharding
but changes memory space to ``pinned_host`` on sleep and back to ``device``
on wake — on TPU this is a DMA over PCIe into pinned buffers, and on
multi-chip meshes each chip's shard moves independently (no resharding, no
gather). Wake does NOT recompile: compiled executables are host-resident and
keyed by sharding+shape, which are unchanged.

**Device release** (`release=True`) goes further than the reference can on
GPU: the state is snapshotted to plain host numpy and the process's PJRT
client is destroyed (`engine/device.py`), so the chip is actually free for
another process — the TPU-correct form of the dual-pods time-sharing
contract (docs/dual-pods.md:20-56; on TPU a chip has exactly one holder, so
an HBM-empty-but-client-open sleeper still blocks every other server). Wake
then re-creates the client, restores state, and re-lowers programs through
the persistent XLA compile cache instead of recompiling from scratch.

Sleep levels (vLLM vocabulary):
  level 1 — weights and KV pages offloaded to host; wake restores both.
  level 2 — weights discarded entirely (re-init/reload on wake), KV dropped.

Backends without host memory-space support (CPU tests) fall back to
numpy staging buffers — same state machine, same API. Release mode works on
every backend (CPU client re-init is supported), so the full release state
machine is exercised by the CPU suite.
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from .device import (
    rebuild_spec,
    reacquire_devices,
    release_devices,
    sharding_spec,
)


class SleepLevel(enum.IntEnum):
    AWAKE = 0
    L1_HOST_OFFLOAD = 1
    L2_DISCARD = 2


def _platform_supports_host_memory() -> bool:
    try:
        dev = jax.devices()[0]
        return any(m.kind == "pinned_host" for m in dev.addressable_memories())
    except Exception:
        return False


@dataclass
class _Stats:
    last_sleep_seconds: float = 0.0
    last_wake_seconds: float = 0.0
    last_reacquire_seconds: float = 0.0
    bytes_offloaded: int = 0
    sleeps_total: int = 0
    wakes_total: int = 0
    releases_total: int = 0


class SleepManager:
    """Owns the awake/asleep state of one engine's device arrays.

    Usage: ``mgr = SleepManager(get_state, set_state)`` where get/set move a
    pytree of device arrays out of / into the engine. The manager guarantees
    the engine never holds both copies (donation/delete on each edge).

    ``on_reacquire`` (optional) runs after a released client is re-created,
    before state restore — the engine uses it to rebuild device-bound
    objects (its mesh).
    """

    def __init__(
        self,
        get_state,
        set_state,
        on_reacquire: Optional[Callable[[], None]] = None,
    ) -> None:
        self._get_state = get_state
        self._set_state = set_state
        self._on_reacquire = on_reacquire
        self._level = SleepLevel.AWAKE
        self._host_state: Optional[Any] = None
        self._shardings: Optional[Any] = None  # sharding objects (no release)
        self._sharding_specs: Optional[Any] = None  # device-free (release)
        #: multi-process offload: per-leaf [(device, np shard), ...] — a
        #: cross-process array is not fully addressable, so each gang
        #: process stages exactly its own shards
        self._staged: Optional[list] = None
        self._staged_meta: Optional[list] = None  # per-leaf (shape, sharding)
        self._treedef: Optional[Any] = None
        self._released = False
        self._use_memory_kind = _platform_supports_host_memory()
        self.stats = _Stats()

    @property
    def is_sleeping(self) -> bool:
        return self._level != SleepLevel.AWAKE

    @property
    def level(self) -> SleepLevel:
        return self._level

    @property
    def devices_released(self) -> bool:
        return self._released

    # -- edges ---------------------------------------------------------------

    def sleep(self, level: int = 1, release: bool = False) -> Dict[str, Any]:
        level = SleepLevel(level)
        if level == SleepLevel.AWAKE:
            raise ValueError("sleep level must be 1 or 2")
        if release and jax.process_count() > 1:
            raise ValueError(
                "device release is not supported for multi-host gangs: "
                "every process would have to drop and re-join the "
                "distributed client in lockstep"
            )
        if self._level != SleepLevel.AWAKE:
            if level == SleepLevel.L2_DISCARD and self._level == SleepLevel.L1_HOST_OFFLOAD:
                # Escalate 1 -> 2: give the host RAM back too.
                if self._use_memory_kind and not self._released and self._host_state is not None:
                    for leaf in jax.tree.leaves(self._host_state):
                        leaf.delete()
                self._host_state = None
                self._staged = None
                self._level = SleepLevel.L2_DISCARD
                self.stats.bytes_offloaded = 0
            return self.describe()
        t0 = time.monotonic()
        state = self._get_state()
        nbytes = sum(x.nbytes for x in jax.tree.leaves(state))
        if release:
            # Plain numpy staging: pinned_host buffers belong to the client
            # we are about to destroy. Save device-free sharding specs as a
            # flat list (the specs are tuples, which pytrees would flatten).
            self._sharding_specs = [
                sharding_spec(x) for x in jax.tree.leaves(state)
            ]
            self._shardings = None
            if level == SleepLevel.L1_HOST_OFFLOAD:
                # one batched fetch (per-leaf np.asarray pays one round
                # trip per array); returns plain numpy, which survives
                # the client destruction below
                self._host_state = jax.device_get(state)
            else:
                self._host_state = None
        elif jax.process_count() > 1:
            # Multi-host gang: every process sleeps in lockstep
            # (engine/multihost.py broadcasts the sleep), each staging its
            # OWN shards — the array is not fully addressable, so neither
            # the memory-kind transfer nor np.asarray of the whole can run.
            self._shardings = None
            self._sharding_specs = None
            if level == SleepLevel.L1_HOST_OFFLOAD:
                leaves, self._treedef = jax.tree.flatten(state)
                shard_lists = [list(x.addressable_shards) for x in leaves]
                # one batched fetch across every leaf's local shards
                datas = jax.device_get(
                    [[s.data for s in shards] for shards in shard_lists]
                )
                self._staged = [
                    [(s.device, d) for s, d in zip(shards, ds)]
                    for shards, ds in zip(shard_lists, datas)
                ]
                self._staged_meta = [(x.shape, x.sharding) for x in leaves]
            else:
                self._staged = None
            self._host_state = None
        else:
            self._shardings = jax.tree.map(lambda x: x.sharding, state)
            self._sharding_specs = None
            if level == SleepLevel.L1_HOST_OFFLOAD:
                if self._use_memory_kind:
                    # one batched transfer: per-leaf device_puts pay one
                    # round trip per array on high-latency links
                    host = jax.device_put(
                        state,
                        jax.tree.map(
                            lambda x: x.sharding.with_memory_kind(
                                "pinned_host"
                            ),
                            state,
                        ),
                    )
                    host = jax.block_until_ready(host)
                else:
                    host = jax.tree.map(lambda x: np.asarray(x), state)
                self._host_state = host
            else:
                self._host_state = None
        # Release HBM now, not at GC time.
        for leaf in jax.tree.leaves(state):
            leaf.delete()
        del state
        self._set_state(None)
        if release:
            release_devices()
            self._released = True
            self.stats.releases_total += 1
        self._level = level
        self.stats.last_sleep_seconds = time.monotonic() - t0
        self.stats.bytes_offloaded = nbytes if level == SleepLevel.L1_HOST_OFFLOAD else 0
        self.stats.sleeps_total += 1
        return self.describe()

    def wake_up(self, reinit=None) -> Dict[str, Any]:
        """Restore device state. For level-2 sleep, `reinit()` must rebuild
        the state (e.g. re-read the checkpoint)."""
        if self._level == SleepLevel.AWAKE:
            return self.describe()
        t0 = time.monotonic()
        if self._released:
            reacquire_devices()
            self.stats.last_reacquire_seconds = time.monotonic() - t0
            if self._on_reacquire is not None:
                self._on_reacquire()
        if self._level == SleepLevel.L1_HOST_OFFLOAD and self._staged is not None:
            # multi-process restore: reassemble each global array from this
            # process's staged shards (every gang process does the same)
            from jax import make_array_from_single_device_arrays

            # one batched upload of every leaf's local shards
            all_arrs = jax.device_put(
                [[buf for _, buf in shards] for shards in self._staged],
                [[d for d, _ in shards] for shards in self._staged],
            )
            restored = []
            for (shape, sharding), arrs in zip(self._staged_meta, all_arrs):
                restored.append(
                    make_array_from_single_device_arrays(shape, sharding, arrs)
                )
            state = jax.tree.unflatten(self._treedef, restored)
            state = jax.block_until_ready(state)
            self._staged = None
            self._staged_meta = None
            self._treedef = None
        elif self._level == SleepLevel.L1_HOST_OFFLOAD:
            assert self._host_state is not None
            if self._released:
                assert self._sharding_specs is not None
                leaves, treedef = jax.tree.flatten(self._host_state)
                restored = jax.device_put(
                    leaves,
                    [rebuild_spec(spec) for spec in self._sharding_specs],
                )
                state = jax.tree.unflatten(treedef, restored)
                state = jax.block_until_ready(state)
            else:
                # batched: one transfer call for the whole tree (see sleep)
                state = jax.device_put(self._host_state, self._shardings)
                state = jax.block_until_ready(state)
                if self._use_memory_kind:
                    for leaf in jax.tree.leaves(self._host_state):
                        leaf.delete()
        else:
            if reinit is None:
                raise ValueError("level-2 wake requires a reinit callback")
            state = reinit()
        self._host_state = None
        self._sharding_specs = None
        self._shardings = None
        self._released = False
        self._set_state(state)
        self._level = SleepLevel.AWAKE
        self.stats.last_wake_seconds = time.monotonic() - t0
        self.stats.wakes_total += 1
        return self.describe()

    def describe(self) -> Dict[str, Any]:
        return {
            "is_sleeping": self.is_sleeping,
            "level": int(self._level),
            "devices_released": self._released,
            "bytes_offloaded": self.stats.bytes_offloaded,
            "last_sleep_seconds": self.stats.last_sleep_seconds,
            "last_wake_seconds": self.stats.last_wake_seconds,
            "last_reacquire_seconds": self.stats.last_reacquire_seconds,
        }


def attach_sleep(engine) -> SleepManager:
    """Wire a SleepManager to an InferenceEngine: the offloadable state is
    (params, kv page pool). Page tables / host bookkeeping stay put, so the
    wake fast path resumes in-flight sequences."""

    def get_state():
        # a dispatched-but-unread decode chunk would be lost with the
        # device state: complete it (emitting its tokens) before offload
        engine.drain_inflight()
        return {"params": engine.params, "kv": engine.pool.as_tuple()}

    def set_state(state):
        if state is None:
            engine.params = None
            engine.pool.k_pages = None
            engine.pool.v_pages = None
            # Scheduler arrays (tokens/positions/budgets/key) are device
            # state too — a sleeping engine must hold zero HBM. Host mirrors
            # stay authoritative; the first post-wake chunk re-uploads them.
            engine.drop_device_sched_state()
        else:
            engine.params = state["params"]
            engine.pool.replace(state["kv"])

    return SleepManager(
        get_state, set_state, on_reacquire=engine.on_device_reacquire
    )
