"""Sleep/wake: move live model state HBM <-> host without killing the
process — optionally releasing the TPU itself.

The reference's headline capability (vLLM sleep mode: ~3 s wake for 64 GiB,
README.md:16-26), rebuilt on XLA memory kinds: every array keeps its sharding
but changes memory space to ``pinned_host`` on sleep and back to ``device``
on wake — on TPU this is a DMA over PCIe into pinned buffers, and on
multi-chip meshes each chip's shard moves independently (no resharding, no
gather). Wake does NOT recompile: compiled executables are host-resident and
keyed by sharding+shape, which are unchanged.

**Device release** (`release=True`) goes further than the reference can on
GPU: the state is snapshotted to plain host numpy and the process's PJRT
client is destroyed (`engine/device.py`), so the chip is actually free for
another process — the TPU-correct form of the dual-pods time-sharing
contract (docs/dual-pods.md:20-56; on TPU a chip has exactly one holder, so
an HBM-empty-but-client-open sleeper still blocks every other server). Wake
then re-creates the client, restores state, and re-lowers programs through
the persistent XLA compile cache instead of recompiling from scratch.

Sleep levels (vLLM vocabulary):
  level 1 — weights and KV pages offloaded to host; wake restores both.
  level 2 — weights discarded entirely (re-init/reload on wake), KV dropped.

Backends without host memory-space support (CPU tests) fall back to
numpy staging buffers — same state machine, same API. Release mode works on
every backend (CPU client re-init is supported), so the full release state
machine is exercised by the CPU suite.

**Chunked transfers** (``bucket_bytes``): the offloadable pytree is split
into size-bounded buckets of whole leaves and moved bucket-by-bucket, each
bucket's HBM freed (offload) or host copy released (wake) as soon as it
lands. This bounds the peak duplicated state to ~one bucket instead of a
whole model tree, and bounds the in-flight transfer window (the
SLO-guarantee lever from "Memory Offloading for LLM Inference with Latency
SLO Guarantees", PAPERS.md). ``bucket_bytes=None`` keeps the legacy
whole-tree single batched transfer. ``swap_states`` builds on the same
buckets to overlap one model's offload with another's restore — the
hot-swap fast path (docs/engine.md "Model hot-swap").
"""

from __future__ import annotations

import enum
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..models import quant as transfer_quant
from ..utils import faults, tracing
from .device import (
    rebuild_spec,
    reacquire_devices,
    release_devices,
    sharding_spec,
)


class SwapRolledBack(RuntimeError):
    """A mid-transfer hot-swap failure was rolled back: the outgoing model
    is fully back on device (awake, serving) and the incoming model's
    host-resident state is intact (re-poolable). The swap did not happen,
    but nothing was lost — retryable."""


class SwapRollbackFailed(RuntimeError):
    """A mid-transfer hot-swap failure could NOT be rolled back: device
    state is partially moved and unrecoverable in-process. The service
    must fail loudly (flip /health) so the controller heals the process."""

#: Default transfer bucket for chunked/overlapped swaps: large enough to
#: amortize per-transfer dispatch, small enough that peak extra HBM and the
#: in-flight window stay a fraction of any serving-size model.
DEFAULT_SWAP_BUCKET_BYTES = 256 << 20


def partition_buckets(
    nbytes: Sequence[int], bucket_bytes: Optional[int]
) -> List[List[int]]:
    """Greedy contiguous partition of leaf indices into buckets of at most
    ``bucket_bytes`` each. Leaves are never split (bit-exactness is then
    structural), so a single leaf larger than the bound forms its own
    bucket. ``bucket_bytes=None`` (or <= 0) returns one bucket holding
    everything — the whole-tree legacy path.

    Shared transfer discipline: the streaming cold-start loader
    (models/hf.py) buckets its host->device stream with this same
    partition, so sleep/wake, hot-swap, and cold load all bound their
    in-flight window the same way."""
    if not nbytes:
        return []
    if not bucket_bytes or bucket_bytes <= 0:
        return [list(range(len(nbytes)))]
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i, nb in enumerate(nbytes):
        if cur and cur_bytes + nb > bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nb
    if cur:
        buckets.append(cur)
    return buckets


def _aligned(state, digests):
    """Per-leaf digest list aligned with the flatten order of ``state``
    (weight digests live under the "params" subtree; KV and scheduler
    leaves get None and always move)."""
    from .chunk_store import aligned_digests

    return aligned_digests(state, digests, prefix="params")


class SleepLevel(enum.IntEnum):
    AWAKE = 0
    L1_HOST_OFFLOAD = 1
    L2_DISCARD = 2


def _platform_supports_host_memory() -> bool:
    try:
        dev = jax.devices()[0]
        return any(m.kind == "pinned_host" for m in dev.addressable_memories())
    except Exception:
        return False


@dataclass
class _Stats:
    last_sleep_seconds: float = 0.0
    last_wake_seconds: float = 0.0
    last_reacquire_seconds: float = 0.0
    #: host bytes the slept state actually occupies (the quantized payload
    #: bytes when --sleep-quant compressed the offload)
    bytes_offloaded: int = 0
    #: full-precision bytes of the state that went to sleep (==
    #: bytes_offloaded for uncompressed offloads)
    bytes_offloaded_full: int = 0
    #: transfer mode of the last level-1 offload: "off" | "int8" | "fp8"
    last_quant: str = "off"
    #: wire bytes the last wake moved host->device
    last_wake_bytes: int = 0
    #: the pure d2h transfer window of the last level-1 offload (the
    #: engine quiesce and device release that last_sleep_seconds also
    #: covers are excluded) — what the cost oracle's bandwidth EWMA and
    #: the phase=d2h histogram observe
    last_sleep_transfer_s: float = 0.0
    #: the pure h2d window of the last wake (client reacquisition
    #: excluded) — the phase=h2d / wake.h2d figure
    last_wake_transfer_s: float = 0.0
    sleeps_total: int = 0
    wakes_total: int = 0
    releases_total: int = 0


class SleepManager:
    """Owns the awake/asleep state of one engine's device arrays.

    Usage: ``mgr = SleepManager(get_state, set_state)`` where get/set move a
    pytree of device arrays out of / into the engine. The manager guarantees
    the engine never holds both copies (donation/delete on each edge).

    ``on_reacquire`` (optional) runs after a released client is re-created,
    before state restore — the engine uses it to rebuild device-bound
    objects (its mesh).

    ``bucket_bytes`` (optional) chunks offload and restore into size-bounded
    transfer buckets (see module docstring); None = whole-tree transfers.
    """

    def __init__(
        self,
        get_state,
        set_state,
        on_reacquire: Optional[Callable[[], None]] = None,
        bucket_bytes: Optional[int] = None,
        quant_mode: str = "off",
        quant_hot_head: bool = True,
        on_transfer: Optional[Callable[[str, int, float], None]] = None,
        peek_state: Optional[Callable[[], Any]] = None,
    ) -> None:
        self._get_state = get_state
        self._set_state = set_state
        self._on_reacquire = on_reacquire
        self.bucket_bytes = bucket_bytes
        #: cost-oracle feed (utils/costs.py): ``on_transfer(kind, bytes,
        #: seconds)`` fires after each completed transfer window
        #: (sleep.d2h / wake.h2d / swap.d2h / swap.h2d) with the WIRE
        #: bytes and wall seconds that window actually took — the
        #: measured GiB/s the pre-transfer pricing divides by. Best
        #: effort: a raising callback never fails an actuation.
        self.on_transfer = on_transfer
        #: side-effect-free state reader for pricing (``plan_swap``):
        #: the default ``get_state`` may quiesce the engine (drain an
        #: in-flight decode chunk), which a dry-run must never do
        self._peek_state = peek_state or get_state
        #: compressed actuation (docs/perf.md "Compressed actuation"):
        #: level-1 offloads quantize eligible weight leaves to int8/fp8 on
        #: device, only the payload crosses the boundary, and wake
        #: dequantizes on device. "off" (default) keeps every transfer
        #: bit-exact.
        self.quant_mode = "" if quant_mode in ("", "off") else quant_mode
        self.quant_hot_head = quant_hot_head
        #: per-leaf TransferQuant-or-None aligned with the flatten order of
        #: ``_host_state`` while quantized-slept (None = fully fp sleep)
        self._quant_meta: Optional[list] = None
        #: int8 scales cached across cycles (aligned with the state's
        #: flatten order): re-quantizing with the SAME scale makes every
        #: cycle after the first reproduce identical payload bits
        self._quant_scales: Optional[list] = None
        self._level = SleepLevel.AWAKE
        self._host_state: Optional[Any] = None
        self._shardings: Optional[Any] = None  # sharding objects (no release)
        self._sharding_specs: Optional[Any] = None  # device-free (release)
        #: multi-process offload: per-leaf [(device, np shard), ...] — a
        #: cross-process array is not fully addressable, so each gang
        #: process stages exactly its own shards
        self._staged: Optional[list] = None
        self._staged_meta: Optional[list] = None  # per-leaf (shape, sharding)
        self._treedef: Optional[Any] = None
        self._released = False
        self._use_memory_kind = _platform_supports_host_memory()
        self.stats = _Stats()

    @property
    def is_sleeping(self) -> bool:
        return self._level != SleepLevel.AWAKE

    @property
    def level(self) -> SleepLevel:
        return self._level

    @property
    def devices_released(self) -> bool:
        return self._released

    def _notify_transfer(
        self, kind: str, nbytes: int, seconds: float
    ) -> None:
        """Feed one completed transfer window to the cost oracle's
        bandwidth EWMAs; zero-byte / zero-time windows and callback
        failures are dropped (telemetry must never fail an edge)."""
        if self.on_transfer is None or nbytes <= 0 or seconds <= 0:
            return
        try:
            self.on_transfer(kind, nbytes, seconds)
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            pass

    # -- chunked transfer primitives -----------------------------------------

    def _quant_plan(self, state) -> Optional[list]:
        """Per-leaf quantize-for-transfer flags for this state, or None
        when the mode is off / nothing is eligible (multi-host staged
        offloads never quantize — shards reassemble bit-for-bit).
        Single-process tp meshes DO quantize: the quantize/dequantize
        ops run shard-local on device (models/quant.py:quantize_leaf)
        and only the payload's shards cross the boundary."""
        if not self.quant_mode or jax.process_count() > 1:
            return None
        plan = transfer_quant.transfer_quant_plan(
            state, hot_head=self.quant_hot_head
        )
        return plan if any(plan) else None

    def _cached_scale(self, i: int, leaf) -> Optional[Any]:
        """The int8 scale this leaf quantized with on its first offload
        (idempotence: same scale -> same payload bits every cycle); None
        until then or when the state structure changed."""
        if self._quant_scales is None or i >= len(self._quant_scales):
            return None
        s = self._quant_scales[i]
        if s is None:
            return None
        want = tuple(leaf.shape[: len(leaf.shape) - 2]) + (
            1,
            leaf.shape[-1],
        )
        return s if tuple(s.shape) == want else None

    def _note_wake_quant(self, metas: Optional[list]) -> None:
        """After a quantized wake (or swap commit): remember the scales so
        the next offload re-quantizes to identical bits, and drop the
        now-consumed payload metadata."""
        if metas is not None and any(m is not None for m in metas):
            self._quant_scales = [
                (m.scale if m is not None else None) for m in metas
            ]
        self._quant_meta = None

    def _offload_leaves(
        self, leaves: list, to_numpy: bool, plan: Optional[list] = None
    ) -> tuple:
        """Device -> host, bucket by bucket: each bucket's device HBM is
        freed as soon as its host copy lands, so peak duplicated state is
        ~one bucket (whole tree when bucket_bytes is None — one batched
        transfer, the round-trip-optimal default on high-latency links).

        ``to_numpy`` stages into plain numpy (release path / no
        memory-kind backend); otherwise into pinned_host jax arrays.

        ``plan`` (per-leaf flags from :meth:`_quant_plan`) quantizes the
        flagged leaves ON DEVICE first, so only the int8/fp8 payload
        crosses the boundary. Returns ``(host_leaves, metas)`` — metas is
        the aligned TransferQuant-or-None list (None when no plan)."""
        host: list = [None] * len(leaves)
        metas: Optional[list] = [None] * len(leaves) if plan else None
        mode = self.quant_mode

        def wire_nb(i):
            if plan and plan[i]:
                return transfer_quant.payload_nbytes(leaves[i].shape, mode)
            return leaves[i].nbytes

        buckets = partition_buckets(
            [wire_nb(i) for i in range(len(leaves))], self.bucket_bytes
        )
        # tracing hoisted out of the bucket loop: disabled = zero per-chunk
        # allocations on this hot path (utils/tracing.py)
        traced = tracing.enabled()
        parent = tracing.current_context() if traced else None
        for bucket in buckets:
            sp = None
            if traced:
                sp = tracing.begin(
                    "sleep.d2h", parent=parent, activate=False,
                    bytes=sum(wire_nb(i) for i in bucket),
                    leaves=len(bucket),
                )
            payload_devs: list = []
            try:
                srcs = []
                for i in bucket:
                    if plan and plan[i]:
                        p, meta = transfer_quant.quantize_leaf(
                            leaves[i], mode,
                            scale=self._cached_scale(i, leaves[i]),
                        )
                        metas[i] = meta
                        payload_devs.append(p)
                        srcs.append(p)
                    else:
                        srcs.append(leaves[i])
                if to_numpy:
                    # force materialized copies: device_get can return
                    # views aliasing the device buffer on CPU-family
                    # backends, and a staging buffer must survive the
                    # buffer delete below (and client destruction on the
                    # release path) on its own
                    copies = [
                        np.array(h, copy=True)
                        for h in jax.device_get(srcs)
                    ]
                else:
                    copies = jax.device_put(
                        srcs,
                        [
                            s.sharding.with_memory_kind("pinned_host")
                            for s in srcs
                        ],
                    )
                    copies = jax.block_until_ready(copies)
            except BaseException as e:
                # the failing bucket is what a failed-sleep trace must
                # show (same discipline as the swap/coldload paths)
                if sp is not None:
                    sp.set(error=f"{type(e).__name__}: {e}")
                    sp.end()
                raise
            for i, h in zip(bucket, copies):
                host[i] = h
            for p in payload_devs:
                p.delete()  # the on-device staging payload served its copy
            for i in bucket:
                leaves[i].delete()
            if sp is not None:
                sp.end()
        return host, metas

    def _restore_leaves(
        self,
        leaves: list,
        targets: list,
        free_host: bool,
        metas: Optional[list] = None,
    ) -> list:
        """Host -> device, bucket by bucket: each bucket blocks before the
        next is issued (bounds the in-flight transfer window) and, with
        ``free_host``, releases its pinned-host source as it lands.

        ``metas`` (aligned TransferQuant-or-None) marks quantized-payload
        leaves: the payload moves H2D, then dequantizes ON DEVICE — the
        dequant of bucket k is dispatched async and rides under bucket
        k+1's transfer, the same overlap discipline AOT warmup uses. On
        meshes the payload lands pre-sharded (device_put to the leaf's
        original NamedSharding) and the expansion runs shard-local; a
        payload recording a shard view (meta.spec) is cross-checked
        against its placement target — expanding under a different
        sharding than it quantized from must fail loudly, never serve."""
        if metas is not None:
            for i, m in enumerate(metas):
                if m is None or m.spec is None:
                    continue
                tspec = getattr(targets[i], "spec", None)
                if tspec is not None and str(tspec) != m.spec:
                    raise RuntimeError(
                        f"quantized payload {i} was sharded {m.spec} but "
                        f"would restore to {tspec}"
                    )
        out: list = [None] * len(leaves)
        buckets = partition_buckets(
            [x.nbytes for x in leaves], self.bucket_bytes
        )
        traced = tracing.enabled()
        parent = tracing.current_context() if traced else None
        deq_payloads: list = []  # device payloads to free once dequants land
        for bucket in buckets:
            sp = None
            if traced:
                sp = tracing.begin(
                    "wake.h2d", parent=parent, activate=False,
                    bytes=sum(leaves[i].nbytes for i in bucket),
                    leaves=len(bucket),
                )
            try:
                restored = jax.device_put(
                    [leaves[i] for i in bucket],
                    [targets[i] for i in bucket],
                )
                restored = jax.block_until_ready(restored)
            except BaseException as e:
                if sp is not None:
                    sp.set(error=f"{type(e).__name__}: {e}")
                    sp.end()
                raise
            for i, d in zip(bucket, restored):
                if metas is not None and metas[i] is not None:
                    # async dispatch: the expansion runs while the next
                    # bucket's H2D is in flight
                    out[i] = transfer_quant.dequantize_leaf(d, metas[i])
                    deq_payloads.append(d)
                else:
                    out[i] = d
            if free_host:
                for i in bucket:
                    leaves[i].delete()
            if sp is not None:
                sp.end()
        if deq_payloads:
            t_dq = time.monotonic()
            jax.block_until_ready([o for o in out if o is not None])
            dq_bytes = sum(p.nbytes for p in deq_payloads)
            for p in deq_payloads:
                p.delete()
            # the non-hidden dequant tail (most expansion rode under the
            # bucket transfers): the cost oracle's quant-overhead signal
            self._notify_transfer(
                "quant.dequant", dq_bytes, time.monotonic() - t_dq
            )
        return out

    # -- edges ---------------------------------------------------------------

    def sleep(self, level: int = 1, release: bool = False) -> Dict[str, Any]:
        level = SleepLevel(level)
        if level == SleepLevel.AWAKE:
            raise ValueError("sleep level must be 1 or 2")
        if getattr(getattr(self, "engine", None), "_variants", None):
            # Co-resident variant deltas are not part of the state tree
            # this manager stages: an L1 offload would silently strand
            # them on device, an L2 discard would leak them. The
            # delta-only "offload" IS detach (engine.detach_variant) —
            # zero d2h, the content-addressed host tiers already hold
            # every delta chunk (docs/perf.md "Co-resident sibling
            # variants").
            raise ValueError(
                "engine has attached co-resident variants; detach them "
                "before sleeping (detach is the delta-only offload)"
            )
        if release and jax.process_count() > 1:
            raise ValueError(
                "device release is not supported for multi-host gangs: "
                "every process would have to drop and re-join the "
                "distributed client in lockstep"
            )
        if self._level != SleepLevel.AWAKE:
            if level == SleepLevel.L2_DISCARD and self._level == SleepLevel.L1_HOST_OFFLOAD:
                # Escalate 1 -> 2: give the host RAM back too.
                if self._use_memory_kind and not self._released and self._host_state is not None:
                    for leaf in jax.tree.leaves(self._host_state):
                        leaf.delete()
                self._host_state = None
                # staged multi-host shards (and their reassembly metadata)
                # are host RAM too: escalation must free all of it
                self._staged = None
                self._staged_meta = None
                self._treedef = None
                # the payload metadata dies with the host state; the scale
                # cache too — a level-2 wake reinitializes weights, and
                # stale scales must never quantize fresh content
                self._quant_meta = None
                self._quant_scales = None
                self._level = SleepLevel.L2_DISCARD
                self.stats.bytes_offloaded = 0
                self.stats.bytes_offloaded_full = 0
                self.stats.last_quant = "off"
            return self.describe()
        t0 = time.monotonic()
        state = self._get_state()
        nbytes = sum(x.nbytes for x in jax.tree.leaves(state))
        plan = self._quant_plan(state) if level == SleepLevel.L1_HOST_OFFLOAD else None
        #: the pure offload window (quiesce/release excluded): the
        #: bandwidth figure the cost oracle divides by
        off_window = 0.0
        if release:
            # Plain numpy staging: pinned_host buffers belong to the client
            # we are about to destroy. Save device-free sharding specs as a
            # flat list (the specs are tuples, which pytrees would flatten).
            self._sharding_specs = [
                sharding_spec(x) for x in jax.tree.leaves(state)
            ]
            self._shardings = None
            if level == SleepLevel.L1_HOST_OFFLOAD:
                # batched fetch per bucket (per-leaf np.asarray pays one
                # round trip per array); returns plain numpy, which
                # survives the client destruction below
                leaves, treedef = jax.tree.flatten(state)
                off_t0 = time.monotonic()
                host_leaves, metas = self._offload_leaves(
                    leaves, to_numpy=True, plan=plan
                )
                off_window = time.monotonic() - off_t0
                self._host_state = jax.tree.unflatten(treedef, host_leaves)
                self._quant_meta = metas
            else:
                self._host_state = None
        elif jax.process_count() > 1:
            # Multi-host gang: every process sleeps in lockstep
            # (engine/multihost.py broadcasts the sleep), each staging its
            # OWN shards — the array is not fully addressable, so neither
            # the memory-kind transfer nor np.asarray of the whole can run.
            self._shardings = None
            self._sharding_specs = None
            if level == SleepLevel.L1_HOST_OFFLOAD:
                leaves, self._treedef = jax.tree.flatten(state)
                shard_lists = [list(x.addressable_shards) for x in leaves]
                # one batched fetch across every leaf's local shards
                datas = jax.device_get(
                    [[s.data for s in shards] for shards in shard_lists]
                )
                self._staged = [
                    [(s.device, d) for s, d in zip(shards, ds)]
                    for shards, ds in zip(shard_lists, datas)
                ]
                self._staged_meta = [(x.shape, x.sharding) for x in leaves]
            else:
                self._staged = None
            self._host_state = None
        else:
            self._shardings = jax.tree.map(lambda x: x.sharding, state)
            self._sharding_specs = None
            if level == SleepLevel.L1_HOST_OFFLOAD:
                # batched transfer per bucket (whole tree = one bucket by
                # default: per-leaf device_puts pay one round trip per
                # array on high-latency links); device HBM is freed
                # bucket-by-bucket inside _offload_leaves
                leaves, treedef = jax.tree.flatten(state)
                off_t0 = time.monotonic()
                host_leaves, metas = self._offload_leaves(
                    leaves, to_numpy=not self._use_memory_kind, plan=plan
                )
                off_window = time.monotonic() - off_t0
                self._host_state = jax.tree.unflatten(treedef, host_leaves)
                self._quant_meta = metas
            else:
                self._host_state = None
        # Release HBM now, not at GC time (chunked offload already deleted
        # its leaves bucket-by-bucket; delete() is idempotent on them).
        for leaf in jax.tree.leaves(state):
            leaf.delete()
        del state
        self._set_state(None)
        if release:
            release_devices()
            self._released = True
            self.stats.releases_total += 1
        self._level = level
        self.stats.last_sleep_seconds = time.monotonic() - t0
        if level == SleepLevel.L1_HOST_OFFLOAD:
            self.stats.bytes_offloaded_full = nbytes
            if self._host_state is not None and self._quant_meta is not None:
                # actual host residency: payload + scale bytes for the
                # quantized leaves, full precision for the rest
                self.stats.bytes_offloaded = sum(
                    x.nbytes for x in jax.tree.leaves(self._host_state)
                ) + sum(
                    m.scale_nbytes
                    for m in self._quant_meta
                    if m is not None
                )
                self.stats.last_quant = self.quant_mode or "off"
            else:
                self.stats.bytes_offloaded = nbytes
                self.stats.last_quant = "off"
        else:
            self.stats.bytes_offloaded = 0
            self.stats.bytes_offloaded_full = 0
            self.stats.last_quant = "off"
        self.stats.sleeps_total += 1
        self.stats.last_sleep_transfer_s = off_window
        if level == SleepLevel.L1_HOST_OFFLOAD and self._staged is None:
            # gang-staged offloads excluded: per-shard staging is not the
            # single-link d2h the oracle prices. The EWMA sees the pure
            # offload window — the engine quiesce (drain_inflight) and a
            # device release also inside last_sleep_seconds would
            # otherwise anchor the d2h bandwidth arbitrarily low.
            self._notify_transfer(
                "sleep.d2h", self.stats.bytes_offloaded, off_window
            )
        return self.describe()

    def wake_up(self, reinit=None) -> Dict[str, Any]:
        """Restore device state. For level-2 sleep, `reinit()` must rebuild
        the state (e.g. re-read the checkpoint)."""
        if self._level == SleepLevel.AWAKE:
            return self.describe()
        restored_from_staged = (
            self._level == SleepLevel.L1_HOST_OFFLOAD
            and self._staged is not None
        )
        t0 = time.monotonic()
        if self._released:
            reacquire_devices()
            self.stats.last_reacquire_seconds = time.monotonic() - t0
            if self._on_reacquire is not None:
                self._on_reacquire()
        if self._level == SleepLevel.L1_HOST_OFFLOAD and self._staged is not None:
            # multi-process restore: reassemble each global array from this
            # process's staged shards (every gang process does the same)
            from jax import make_array_from_single_device_arrays

            # this process's restore figures (the _host_state branch sets
            # its own below): without them a gang wake's flight record
            # would carry stale/zero bytes
            self.stats.last_wake_bytes = sum(
                buf.nbytes for shards in self._staged for _, buf in shards
            )
            t_restore0 = time.monotonic()
            # one batched upload of every leaf's local shards
            all_arrs = jax.device_put(
                [[buf for _, buf in shards] for shards in self._staged],
                [[d for d, _ in shards] for shards in self._staged],
            )
            restored = []
            for (shape, sharding), arrs in zip(self._staged_meta, all_arrs):
                restored.append(
                    make_array_from_single_device_arrays(shape, sharding, arrs)
                )
            state = jax.tree.unflatten(self._treedef, restored)
            state = jax.block_until_ready(state)
            self.stats.last_wake_transfer_s = time.monotonic() - t_restore0
            self._staged = None
            self._staged_meta = None
            self._treedef = None
        elif self._level == SleepLevel.L1_HOST_OFFLOAD:
            assert self._host_state is not None
            leaves, treedef = jax.tree.flatten(self._host_state)
            metas = self._quant_meta
            self.stats.last_wake_bytes = sum(x.nbytes for x in leaves) + (
                sum(m.scale_nbytes for m in metas if m is not None)
                if metas is not None
                else 0
            )
            if self._released:
                assert self._sharding_specs is not None
                # bucket-by-bucket: shardings are rebuilt on the fresh
                # client and each bucket lands before the next is issued
                # (bounded in-flight window; whole tree = one bucket by
                # default)
                restored = self._restore_leaves(
                    leaves,
                    [rebuild_spec(spec) for spec in self._sharding_specs],
                    free_host=False,
                    metas=metas,
                )
                state = jax.tree.unflatten(treedef, restored)
            else:
                # batched transfer per bucket (see sleep); pinned-host
                # sources are released as their bucket lands
                shardings, _ = jax.tree.flatten(self._shardings)
                restored = self._restore_leaves(
                    leaves, shardings, free_host=self._use_memory_kind,
                    metas=metas,
                )
                state = jax.tree.unflatten(treedef, restored)
            self._note_wake_quant(metas)
        else:
            if reinit is None:
                raise ValueError("level-2 wake requires a reinit callback")
            # fresh state: cached scales describe weights that no longer
            # exist and must never quantize the reinitialized content
            self._quant_scales = None
            self._quant_meta = None
            state = reinit()
        restored_from_host = (
            self._level == SleepLevel.L1_HOST_OFFLOAD
            and self._host_state is not None
        )
        was_released = self._released
        self._host_state = None
        self._sharding_specs = None
        self._shardings = None
        self._released = False
        self._set_state(state)
        self._level = SleepLevel.AWAKE
        self.stats.last_wake_seconds = time.monotonic() - t0
        self.stats.wakes_total += 1
        if restored_from_host:
            # the h2d window excludes client reacquisition (release
            # path): the oracle prices bytes-over-the-link, and a wake
            # after device release pays reacquire separately
            self.stats.last_wake_transfer_s = max(
                0.0,
                self.stats.last_wake_seconds
                - (
                    self.stats.last_reacquire_seconds
                    if was_released
                    else 0.0
                ),
            )
            self._notify_transfer(
                "wake.h2d",
                self.stats.last_wake_bytes,
                self.stats.last_wake_transfer_s,
            )
        elif not restored_from_staged:
            # reinit (level-2) wake: no host payload moved; the staged
            # (gang) branch set its own figures and stays out of the
            # single-link EWMA by design
            self.stats.last_wake_transfer_s = 0.0
            self.stats.last_wake_bytes = 0
        return self.describe()

    def warm_quant_ops(self) -> int:
        """Run the transfer quantize/dequantize graphs once per distinct
        eligible (shape, dtype) over the engine's REAL leaves (the op
        cache distinguishes the live committed arrays from synthetic
        stand-ins), so the FIRST real quantized actuation doesn't pay
        their one-time op compiles inside its transfer window — and the
        cost oracle's first measured bandwidth windows describe
        steady-state transfer, not compile stalls (utils/costs.py). All
        three graphs warm: fresh-scale quantize, cached-scale
        re-quantize (what every cycle after the first runs), and the
        on-device dequant. quantize_leaf is pure — the weights are read,
        never changed; peak extra HBM is one payload per shape, freed
        leaf-by-leaf. No-op when quant is off or in a gang. Returns the
        number of distinct shapes warmed."""
        if not self.quant_mode or jax.process_count() > 1:
            return 0
        state = self._peek_state()
        plan = self._quant_plan(state)
        if not plan:
            return 0
        leaves = jax.tree.leaves(state)
        seen = set()
        for leaf, flagged in zip(leaves, plan):
            if not flagged:
                continue
            key = (tuple(leaf.shape), str(leaf.dtype))
            if key in seen:
                continue
            seen.add(key)
            p, meta = transfer_quant.quantize_leaf(leaf, self.quant_mode)
            p2, _ = transfer_quant.quantize_leaf(
                leaf, self.quant_mode, scale=meta.scale
            )
            d = transfer_quant.dequantize_leaf(p, meta)
            jax.block_until_ready(d)
            for a in (p, p2, d):
                a.delete()
        return len(seen)

    def quant_state(self) -> str:
        """Transfer mode of the currently-slept payload ("off" when the
        host state is full precision / not level-1 slept)."""
        if self._quant_meta is not None and any(
            m is not None for m in self._quant_meta
        ):
            return self._quant_meta[
                next(
                    i for i, m in enumerate(self._quant_meta)
                    if m is not None
                )
            ].mode
        return "off"

    def describe(self) -> Dict[str, Any]:
        return {
            "is_sleeping": self.is_sleeping,
            "level": int(self._level),
            "devices_released": self._released,
            "bytes_offloaded": self.stats.bytes_offloaded,
            "bytes_offloaded_full": self.stats.bytes_offloaded_full,
            "quant": self.stats.last_quant,
            "last_sleep_seconds": self.stats.last_sleep_seconds,
            "last_wake_seconds": self.stats.last_wake_seconds,
            "last_reacquire_seconds": self.stats.last_reacquire_seconds,
        }


@dataclass
class _TransferPlan:
    """Byte-exact schedule of one hot-swap transfer, computed from
    shapes / dtypes / shardings / digests alone — no data read, no byte
    moved. Shared by the executing :func:`swap_states` and the dry-run
    :func:`plan_swap` (the cost oracle's pre-transfer pricing), so a
    priced swap and the swap it prices can never disagree on bytes."""

    qmode: str  #: "" or the transfer-quant mode in effect
    out_plan: Optional[list]  #: per-leaf on-device quantize flags (out)
    #: per-leaf host-staging quantize flags for a full-precision
    #: incoming entry under quant mode (None when not applicable); only
    #: the moving leaves are actually staged
    in_stage_plan: Optional[list]
    in_metas: list  #: pre-existing TransferQuant-or-None (quantized-slept)
    reuse_pairs: List[tuple]  #: (incoming idx, outgoing idx) digest matches
    move_out: List[int]
    move_in: List[int]
    nb_out: List[int]
    nb_in: List[int]
    wnb_out: List[int]  #: wire bytes per outgoing leaf
    wnb_in: List[int]  #: wire bytes per incoming leaf
    buckets_out: List[List[int]]
    buckets_in: List[List[int]]
    bytes_out: int
    bytes_in: int
    bytes_full: int
    deduped_bytes: int
    moved_bytes: int
    quant_leaves: int
    quant_active: bool
    quant_mode_used: str


def _plan_transfer(
    out_mgr: SleepManager,
    in_mgr: SleepManager,
    state_out: Any,
    leaves_out: list,
    shard_out: list,
    nb_out: List[int],
    in_host_state: Any,
    leaves_in: list,
    shard_in: list,
    nb_in: List[int],
    bucket_bytes: int,
    out_digests: Optional[Dict[str, str]],
    in_digests: Optional[Dict[str, str]],
    quant: Optional[str],
) -> _TransferPlan:
    """The planning phase of a hot-swap (see :func:`swap_states` for the
    semantics of delta matching and quantized staging): which leaves
    move, which are digest-matched away, and exactly how many wire bytes
    each direction carries. Pure — reads shapes/digests only."""
    qmode = quant if quant is not None else (out_mgr.quant_mode or "off")
    qmode = "" if qmode in ("", "off") else qmode
    out_plan = out_mgr._quant_plan(state_out) if qmode else None
    in_metas: list = (
        list(in_mgr._quant_meta)
        if in_mgr._quant_meta is not None
        else [None] * len(leaves_in)
    )

    # Delta matching (swap_states docstring): pair incoming leaves with
    # content-identical live outgoing leaves by digest; matched pairs are
    # excluded from BOTH transfer directions. A quantized-slept incoming
    # leaf's digest names its ORIGINAL full-precision content, so the
    # dtype check compares against the payload's origin dtype.
    reuse_pairs: List[tuple] = []
    if out_digests and in_digests:
        dl_out = _aligned(state_out, out_digests)
        dl_in = _aligned(in_host_state, in_digests)
        by_digest: Dict[str, List[int]] = {}
        for j, d in enumerate(dl_out):
            if d is not None:
                by_digest.setdefault(d, []).append(j)
        for i, d in enumerate(dl_in):
            cands = by_digest.get(d) if d is not None else None
            if not cands:
                continue
            j = cands[0]
            lo, li = leaves_out[j], leaves_in[i]
            li_dtype = (
                np.dtype(in_metas[i].orig_dtype)
                if in_metas[i] is not None
                else li.dtype
            )
            if (
                tuple(lo.shape) == tuple(li.shape)
                and lo.dtype == li_dtype
                and shard_out[j] == shard_in[i]
            ):
                reuse_pairs.append((i, j))
                cands.pop(0)
    reused_in = {i for i, _ in reuse_pairs}
    reused_out = {j for _, j in reuse_pairs}
    move_out = [i for i in range(len(leaves_out)) if i not in reused_out]
    move_in = [i for i in range(len(leaves_in)) if i not in reused_in]
    move_in_set = set(move_in)

    # Host-side staging quantization applies to a full-precision incoming
    # entry under quant mode — but only its MOVING leaves are staged; the
    # wire bytes of a to-be-staged leaf are exactly payload_nbytes (the
    # int8/fp8 payload plus its scale), predictable from the shape alone.
    in_stage_plan: Optional[list] = None
    if qmode and in_mgr._quant_meta is None:
        in_stage_plan = transfer_quant.transfer_quant_plan(
            in_host_state, hot_head=in_mgr.quant_hot_head
        )

    wnb_out = [
        transfer_quant.payload_nbytes(leaves_out[i].shape, qmode)
        if out_plan and out_plan[i]
        else nb_out[i]
        for i in range(len(leaves_out))
    ]

    def _wire_in(i: int) -> int:
        if in_metas[i] is not None:
            # already a payload (quantized-slept): leaf bytes + scale
            return nb_in[i] + in_metas[i].scale_nbytes
        if in_stage_plan and in_stage_plan[i] and i in move_in_set:
            return transfer_quant.payload_nbytes(leaves_in[i].shape, qmode)
        return nb_in[i]

    wnb_in = [_wire_in(i) for i in range(len(leaves_in))]
    buckets_out = [
        [move_out[k] for k in b]
        for b in partition_buckets(
            [wnb_out[i] for i in move_out], bucket_bytes
        )
    ]
    buckets_in = [
        [move_in[k] for k in b]
        for b in partition_buckets(
            [wnb_in[i] for i in move_in], bucket_bytes
        )
    ]
    bytes_out = sum(wnb_out)
    bytes_in = sum(wnb_in)
    bytes_full = sum(nb_out) + sum(
        nb_in[i]
        if in_metas[i] is None
        else int(
            np.prod(leaves_in[i].shape)
            * np.dtype(in_metas[i].orig_dtype).itemsize
        )
        for i in range(len(leaves_in))
    )
    deduped_bytes = sum(wnb_out[j] for j in reused_out) + sum(
        wnb_in[i] for i in reused_in
    )
    quant_leaves = sum(
        1 for i in move_out if out_plan and out_plan[i]
    ) + sum(
        1
        for i in move_in
        if in_metas[i] is not None
        or (in_stage_plan and in_stage_plan[i])
    )
    quant_active = (
        bool(out_plan)
        or any(m is not None for m in in_metas)
        or bool(
            in_stage_plan
            and any(in_stage_plan[i] for i in move_in)
        )
    )
    quant_mode_used = (
        qmode or next((m.mode for m in in_metas if m is not None), "off")
        if quant_active
        else "off"
    )
    return _TransferPlan(
        qmode=qmode,
        out_plan=out_plan,
        in_stage_plan=in_stage_plan,
        in_metas=in_metas,
        reuse_pairs=reuse_pairs,
        move_out=move_out,
        move_in=move_in,
        nb_out=nb_out,
        nb_in=nb_in,
        wnb_out=wnb_out,
        wnb_in=wnb_in,
        buckets_out=buckets_out,
        buckets_in=buckets_in,
        bytes_out=bytes_out,
        bytes_in=bytes_in,
        bytes_full=bytes_full,
        deduped_bytes=deduped_bytes,
        moved_bytes=bytes_out + bytes_in - deduped_bytes,
        quant_leaves=quant_leaves,
        quant_active=quant_active,
        quant_mode_used=quant_mode_used,
    )


def _check_swap_preconditions(
    out_mgr: SleepManager, in_mgr: SleepManager
) -> None:
    if out_mgr.is_sleeping:
        raise ValueError("swap-out model must be awake")
    if (
        in_mgr.level != SleepLevel.L1_HOST_OFFLOAD
        or in_mgr._host_state is None
    ):
        raise ValueError(
            "swap-in model must be asleep at level 1 with host-resident "
            "state (level-2 / multi-host-staged states cannot hot-swap)"
        )
    if in_mgr._released:
        raise ValueError(
            "swap-in model was released; hot-swap keeps one live client"
        )
    if jax.process_count() > 1:
        raise ValueError("hot-swap is not supported for multi-host gangs")


def plan_swap(
    out_mgr: SleepManager,
    in_mgr: SleepManager,
    bucket_bytes: Optional[int] = None,
    out_digests: Optional[Dict[str, str]] = None,
    in_digests: Optional[Dict[str, str]] = None,
    quant: Optional[str] = None,
) -> Dict[str, Any]:
    """Price a hot-swap WITHOUT moving a byte: the identical planning
    code :func:`swap_states` executes (same preconditions, same delta
    matching, same quantized-payload sizing), run against a
    side-effect-free peek of the outgoing state — so the predicted wire
    bytes are **exact by construction** for any swap the planner can
    see (the delta-sibling and quantized CI gates pin this). Returns the
    byte keys of the swap metrics dict plus bucket counts (what the
    seconds model divides by measured bandwidth)."""
    _check_swap_preconditions(out_mgr, in_mgr)
    bucket_bytes = bucket_bytes or DEFAULT_SWAP_BUCKET_BYTES
    state_out = out_mgr._peek_state()
    leaves_out, _ = jax.tree.flatten(state_out)
    shard_out = [x.sharding for x in leaves_out]
    nb_out = [x.nbytes for x in leaves_out]
    leaves_in, _ = jax.tree.flatten(in_mgr._host_state)
    shard_in, _ = jax.tree.flatten(in_mgr._shardings)
    nb_in = [x.nbytes for x in leaves_in]
    plan = _plan_transfer(
        out_mgr, in_mgr, state_out, leaves_out, shard_out, nb_out,
        in_mgr._host_state, leaves_in, shard_in, nb_in,
        bucket_bytes, out_digests, in_digests, quant,
    )
    return {
        "bytes_out": plan.bytes_out,
        "bytes_in": plan.bytes_in,
        "bytes_moved": plan.moved_bytes,
        "bytes_deduped": plan.deduped_bytes,
        # per-direction bytes that actually cross the device boundary
        # (totals minus the digest-matched leaves): what the seconds
        # model divides by measured per-direction bandwidth
        "wire_out": sum(plan.wnb_out[i] for i in plan.move_out),
        "wire_in": sum(plan.wnb_in[i] for i in plan.move_in),
        "deduped_leaves": len(plan.reuse_pairs),
        "quant": plan.quant_mode_used,
        "quant_leaves": plan.quant_leaves,
        "bytes_full": plan.bytes_full,
        "bytes_saved_quant": max(
            0, plan.bytes_full - (plan.bytes_out + plan.bytes_in)
        ),
        "buckets_out": len(plan.buckets_out),
        "buckets_in": len(plan.buckets_in),
        "bucket_bytes": bucket_bytes,
        "leaves_out": len(leaves_out),
        "leaves_in": len(leaves_in),
    }


def swap_states(
    out_mgr: SleepManager,
    in_mgr: SleepManager,
    bucket_bytes: Optional[int] = None,
    overlapped: bool = True,
    out_digests: Optional[Dict[str, str]] = None,
    in_digests: Optional[Dict[str, str]] = None,
    quant: Optional[str] = None,
) -> Dict[str, Any]:
    """Overlapped model hot-swap: stream the awake model behind ``out_mgr``
    to host while restoring ``in_mgr``'s slept (level-1, non-released) state
    to device, double-buffered over size-bounded buckets.

    Schedule: the device->host DMA of outgoing bucket k runs concurrently
    with the host->device DMA of incoming bucket k-1 (issued into the HBM
    bucket k-1's completion just freed), so swap latency approaches
    max(sleep, wake) instead of sleep + wake and peak extra HBM is bounded
    by ~one bucket. In-flight bytes are bounded by ~3 buckets — the
    double-buffered outgoing pair plus one incoming (the SLO window;
    `peak_bytes_in_flight` in the returned metrics reports the measured
    value).

    On memory-kind backends (TPU) the concurrency comes from jax's async
    transfer dispatch; on the numpy-staging fallback (CPU tests) transfers
    are synchronous, so the incoming direction runs on a worker thread —
    the staging copies release the GIL, making the overlap real there too.

    Ends with ``out_mgr`` asleep at level 1 (host-resident, poolable) and
    ``in_mgr`` awake. Bit-exact: whole leaves move, nothing is recomputed.
    Returns a metrics dict (timings, overlap fraction, bytes, buckets).

    **Transactional**: no destructive operation on the incoming model's
    host state happens before the swap commits (its pinned-host copies are
    freed at commit, not bucket-by-bucket — peak pinned-host during the
    swap is therefore the full incoming model plus the growing outgoing
    copy, the price of recoverability), and the outgoing model's host
    copies always land before their device HBM is freed. A mid-transfer
    failure (HBM OOM, injected ``swap.d2h``/``swap.h2d`` fault) is rolled
    back: partially-restored incoming device buckets are dropped, the
    outgoing model's already-freed device leaves are re-uploaded from
    their host copies, and :class:`SwapRolledBack` is raised — both models
    end exactly as they began. Only a failure *during that rollback*
    raises :class:`SwapRollbackFailed` (state genuinely lost).

    ``overlapped=False`` runs the identical code path on a strictly
    sequential schedule (every outgoing bucket lands before the first
    incoming one is issued) — the measured apples-to-apples baseline the
    swap sub-bench compares against (bench.py).

    **Delta-aware** (``out_digests``/``in_digests``, flat weight key ->
    content digest — engine/chunk_store.py): leaves the two models share
    by content hash never cross the device boundary at all. A matched
    incoming leaf takes OVER the outgoing model's live device array (same
    bytes, by digest), and the incoming pool entry's host copy becomes
    the outgoing model's slept host state — so only the *delta* between
    sibling fine-tune variants moves over PCIe, in both directions.
    Matches additionally require equal shape/dtype/sharding, and the
    reuse is applied only at commit: a rollback sees untouched leaves.
    Reported as ``bytes_moved`` / ``bytes_deduped`` (and the
    ``swap.delta`` trace span). ``None`` digests = the pre-delta full
    transfer, bit-for-bit the old behavior.

    **Quantized transfers** (``quant="int8"|"fp8"``, default = the
    outgoing manager's mode; docs/perf.md "Compressed actuation"):
    eligible outgoing weight leaves quantize ON DEVICE and only the
    payload crosses PCIe; an incoming model slept quantized moves its
    payload and dequantizes ON DEVICE after each bucket lands (the
    expansion rides under the next bucket's transfer); an incoming model
    slept at full precision gets a host-side quantized *staging copy* for
    the transfer while its pooled host state is never touched — a
    rollback re-pools it bit-exact. The transactional contract holds:
    rolled-back outgoing leaves are re-uploaded from their payloads and
    dequantized with the same cached scales, reproducing the exact
    post-quantization bits every cycle after a model's first quantized
    offload (the lossy-once contract). Composes with the delta path:
    digest-matched leaves still skip both directions entirely. Byte
    metrics (``bytes_out``/``bytes_in``/``bytes_moved``) count WIRE
    bytes; ``bytes_full`` carries the uncompressed total and
    ``bytes_saved_quant`` the difference (the ``swap.quant`` span mirrors
    them).
    """
    _check_swap_preconditions(out_mgr, in_mgr)
    bucket_bytes = bucket_bytes or DEFAULT_SWAP_BUCKET_BYTES
    use_mk = out_mgr._use_memory_kind
    # Root span for the transfer phase; per-bucket child spans are created
    # only when tracing is enabled (`traced` hoisted out of the hot loop:
    # the disabled path adds no per-chunk allocations). activate=False:
    # begin/end straddle exception paths, and a leaked ContextVar token
    # would misparent later spans on this (reused executor) thread.
    root = tracing.begin("swap.transfer", activate=False, overlapped=overlapped)
    traced = root is not tracing.NOOP_SPAN
    root_ctx = root.context() if traced else None
    t_begin = time.monotonic()

    state_out = out_mgr._get_state()
    leaves_out, treedef_out = jax.tree.flatten(state_out)
    shard_out = [x.sharding for x in leaves_out]
    # leaf byte counts computed once (nbytes is a non-trivial property on
    # jax arrays) and reused for partitioning, totals, and the in-flight
    # accounting inside the transfer loop
    nb_out = [x.nbytes for x in leaves_out]
    leaves_in, treedef_in = jax.tree.flatten(in_mgr._host_state)
    shard_in, _ = jax.tree.flatten(in_mgr._shardings)
    nb_in = [x.nbytes for x in leaves_in]

    # Planning — quantized-transfer flags, delta matching, and wire-byte
    # sizing — is shared with the cost oracle's dry-run (plan_swap): the
    # exact code that prices a swap is the code that executes it, so
    # predicted and actual wire bytes can never disagree.
    plan = _plan_transfer(
        out_mgr, in_mgr, state_out, leaves_out, shard_out, nb_out,
        in_mgr._host_state, leaves_in, shard_in, nb_in,
        bucket_bytes, out_digests, in_digests, quant,
    )
    qmode = plan.qmode
    out_plan = plan.out_plan
    meta_out: list = [None] * len(leaves_out)
    in_metas = plan.in_metas
    reuse_pairs = plan.reuse_pairs
    reused_in = {i for i, _ in reuse_pairs}
    reused_out = {j for _, j in reuse_pairs}
    move_in = plan.move_in
    wnb_out, wnb_in = plan.wnb_out, plan.wnb_in
    buckets_out, buckets_in = plan.buckets_out, plan.buckets_in

    # Host-side staging quantization for a full-precision incoming entry
    # under quant mode: the payload staging copies move instead of the fp
    # host state, which stays untouched until commit (rollback re-pools it
    # bit-exact). Only leaves that actually move are staged; their wire
    # bytes were already sized by the planner (payload_nbytes — payload
    # plus scale — equals the staged array plus its metadata exactly).
    stage_in: list = [None] * len(leaves_in)
    if plan.in_stage_plan is not None:
        for i in move_in:
            if plan.in_stage_plan[i]:
                stage_in[i], in_metas[i] = transfer_quant.quantize_leaf_np(
                    np.asarray(leaves_in[i]), qmode
                )

    host_out: list = [None] * len(leaves_out)
    dev_in: list = [None] * len(leaves_in)
    bytes_out = plan.bytes_out
    bytes_in = plan.bytes_in
    bytes_full = plan.bytes_full
    deduped_bytes = plan.deduped_bytes
    moved_bytes = plan.moved_bytes
    quant_leaves = plan.quant_leaves
    if reuse_pairs and traced:
        dsp = tracing.begin(
            "swap.delta",
            parent=root_ctx,
            activate=False,
            leaves_shared=len(reuse_pairs),
            bytes_deduped=deduped_bytes,
            bytes_moved=moved_bytes,
        )
        dsp.end()
    quant_active = plan.quant_active
    quant_mode_used = plan.quant_mode_used
    if quant_active and traced:
        qsp = tracing.begin(
            "swap.quant",
            parent=root_ctx,
            activate=False,
            mode=quant_mode_used,
            leaves=quant_leaves,
            bytes_wire=bytes_out + bytes_in,
            bytes_full=bytes_full,
            bytes_saved=max(0, bytes_full - (bytes_out + bytes_in)),
        )
        qsp.end()
    bsize_out = [sum(wnb_out[i] for i in b) for b in buckets_out]
    bsize_in = [sum(wnb_in[i] for i in b) for b in buckets_in]

    in_flight = 0
    peak_in_flight = 0
    d2h_t0 = d2h_t1 = h2d_t0 = h2d_t1 = None

    #: outgoing leaf indices whose device HBM was freed (what a rollback
    #: must re-upload from host_out)
    deleted_out: set = set()
    #: incoming leaf indices whose pinned-host copies are due at commit
    #: (deferred so a rollback can re-pool the incoming entry intact)
    deferred_in_frees: List[int] = []

    def _fail_span(sp, e) -> None:
        """Record a bucket span whose transfer raised: the failing bucket
        is exactly the one a fault-drill trace must show."""
        if sp is not None:
            sp.set(error=f"{type(e).__name__}: {e}")
            sp.end()

    def _issue_d2h(k):
        nonlocal in_flight, peak_in_flight
        sp = None
        if traced:
            sp = tracing.begin(
                "swap.d2h", parent=root_ctx, activate=False,
                bucket=k, bytes=bsize_out[k],
            )
        payload_devs: list = []
        try:
            faults.fire("swap.d2h")
            bucket = buckets_out[k]
            srcs = []
            for i in bucket:
                if out_plan and out_plan[i]:
                    # on-device quantization: only the payload crosses
                    # PCIe; cached scales keep re-quantization bit-stable
                    p, meta = transfer_quant.quantize_leaf(
                        leaves_out[i], qmode,
                        scale=out_mgr._cached_scale(i, leaves_out[i]),
                    )
                    meta_out[i] = meta
                    payload_devs.append(p)
                    srcs.append(p)
                else:
                    srcs.append(leaves_out[i])
            if use_mk:
                copies = jax.device_put(
                    srcs,
                    [
                        s.sharding.with_memory_kind("pinned_host")
                        for s in srcs
                    ],
                )
            else:
                # real copies (not views of the buffers deleted below),
                # same as the SleepManager staging path
                copies = [np.array(s, copy=True) for s in srcs]
        except BaseException as e:
            _fail_span(sp, e)
            raise
        in_flight += bsize_out[k]
        if in_flight > peak_in_flight:
            peak_in_flight = in_flight
        return k, copies, payload_devs, sp

    #: threaded (numpy-staging) mode: outgoing buffer deletes are deferred
    #: to the commit phase so the main thread never mutates client buffer
    #: state while the worker thread is mid-device_put — on these backends
    #: "device" memory is host RAM, so nothing is gained by eager frees
    deferred_deletes: List[int] = []

    #: on-device staging payloads whose frees are deferred in threaded
    #: (numpy-staging) mode — same rule as deferred_deletes below: the
    #: main thread must not mutate client buffer state mid-device_put
    deferred_payload_frees: List[Any] = []

    def _finish_d2h(pending):
        nonlocal in_flight
        k, copies, payload_devs, sp = pending
        bucket = buckets_out[k]
        if use_mk:
            try:
                copies = jax.block_until_ready(copies)
            except BaseException as e:
                _fail_span(sp, e)
                raise
        for i, h in zip(bucket, copies):
            host_out[i] = h
        if h2d_pool is None:
            for p in payload_devs:
                p.delete()  # staging payload: its host copy just landed
            for i in bucket:
                leaves_out[i].delete()  # the HBM the next h2d bucket fills
            deleted_out.update(bucket)
        else:
            deferred_payload_frees.extend(payload_devs)
            deferred_deletes.extend(bucket)
        in_flight -= bsize_out[k]
        if sp is not None:
            sp.end()

    # The incoming direction: async transfer dispatch where the backend
    # has it (memory kinds); a single worker thread where transfers are
    # synchronous (numpy staging), so the overlap stays real. EXCEPT in
    # forked children (the launcher's process model): a fork from a
    # multi-threaded parent inherits a single-threaded snapshot whose
    # other-thread lock state is frozen mid-flight, and spawning transfer
    # threads there intermittently aborts the child — the threaded overlap
    # is a bench-scale concern on this fallback, not a serving-path one.
    import multiprocessing

    use_thread = (
        overlapped
        and not use_mk
        and multiprocessing.parent_process() is None
    )
    h2d_pool = (
        ThreadPoolExecutor(1, thread_name_prefix="swap-h2d")
        if use_thread
        else None
    )

    def _h2d_transfer(j):
        bucket = buckets_in[j]
        # staged payload (host-quantized fp entry) or the host leaf itself
        # (a payload already, for a quantized-slept entry; fp otherwise)
        return jax.device_put(
            [
                stage_in[i] if stage_in[i] is not None else leaves_in[i]
                for i in bucket
            ],
            [shard_in[i] for i in bucket],
        )

    def _issue_h2d(j):
        nonlocal in_flight, peak_in_flight, h2d_t0
        sp = None
        if traced:
            sp = tracing.begin(
                "swap.h2d", parent=root_ctx, activate=False,
                bucket=j, bytes=bsize_in[j],
            )
        try:
            faults.fire("swap.h2d")
            if h2d_t0 is None:
                h2d_t0 = time.monotonic()
            if h2d_pool is not None:
                restored = h2d_pool.submit(_h2d_transfer, j)
            else:
                restored = _h2d_transfer(j)
        except BaseException as e:
            _fail_span(sp, e)
            raise
        in_flight += bsize_in[j]
        if in_flight > peak_in_flight:
            peak_in_flight = in_flight
        return j, restored, sp

    #: device payloads of incoming quantized leaves, freed once their
    #: dequant (dispatched async below) has landed
    in_payload_devs: List[Any] = []

    def _finish_h2d(pending):
        nonlocal in_flight
        j, restored, sp = pending
        bucket = buckets_in[j]
        try:
            if h2d_pool is not None:
                restored = restored.result()
            restored = jax.block_until_ready(restored)
        except BaseException as e:
            _fail_span(sp, e)
            raise
        for i, d in zip(bucket, restored):
            if in_metas[i] is not None:
                # on-device dequant, dispatched async: the expansion to
                # full precision rides under the next bucket's transfers
                dev_in[i] = transfer_quant.dequantize_leaf(d, in_metas[i])
                in_payload_devs.append(d)
            else:
                dev_in[i] = d
        if use_mk:
            # NOT freed here: the incoming pool entry must survive intact
            # until the swap commits, so a mid-transfer failure can put it
            # back untouched
            deferred_in_frees.extend(bucket)
        in_flight -= bsize_in[j]
        if sp is not None:
            sp.end()

    # Double-buffered main loop: while outgoing bucket k drains, incoming
    # bucket k-1 rides the opposite direction into the space k-1 freed.
    # (Sequential mode: the same loop, minus the interleaved h2d issues.)
    pend_d2h = pend_h2d = None
    next_in = 0

    def _rollback() -> None:
        """Undo every side effect of a partial transfer: drop what the
        incoming model landed on device, re-upload the outgoing leaves
        whose HBM was already freed (their host copies land before the
        free, by construction), and reinstall the outgoing state. The
        incoming host tree was never touched (frees are deferred to
        commit), so the pool entry goes back intact."""
        # quiesce the in-flight incoming transfer first: its device_put
        # must land (or fail) before any buffer it touches is reclaimed
        if pend_h2d is not None:
            _, restored, _sp = pend_h2d
            if _sp is not None and not _sp.ended:
                # a span already failed by _finish_h2d keeps its error
                # attr; a genuinely in-flight one is recorded as cut
                # short by the rollback
                _sp.set(error="rolled_back")
                _sp.end()
            try:
                if h2d_pool is not None:
                    restored = restored.result()
                for a in jax.block_until_ready(restored):
                    a.delete()
            except Exception:  # noqa: BLE001 — the failed transfer itself
                pass
        if h2d_pool is not None:
            h2d_pool.shutdown(wait=True)
        # the in-flight outgoing copy: let it land and keep the host copy
        # (its device leaves are only deleted by _finish_d2h, which did
        # not run for a still-pending bucket)
        if pend_d2h is not None:
            k, copies, pdevs, _sp = pend_d2h
            if _sp is not None and not _sp.ended:
                _sp.set(error="rolled_back")
                _sp.end()
            try:
                if use_mk:
                    copies = jax.block_until_ready(copies)
                for i, h in zip(buckets_out[k], copies):
                    host_out[i] = h
                for p in pdevs:
                    p.delete()
            except Exception:  # noqa: BLE001 — the failed transfer itself
                pass
        try:
            # quantized incoming leaves have async dequants in flight:
            # they must land (or fail) before their arrays are reclaimed
            jax.block_until_ready([a for a in dev_in if a is not None])
        except Exception:  # noqa: BLE001 — a failed dequant is dropped too
            pass
        for a in dev_in:
            if a is not None:
                a.delete()
        for p in in_payload_devs:
            p.delete()
        # re-upload freed outgoing leaves, bucket-by-bucket (same bounded
        # in-flight window as the forward direction). Quantized leaves
        # re-upload their payload and dequantize on device: the cached
        # scales make the result bit-identical to the post-quantization
        # weights every cycle after the model's first quantized offload
        # (the lossy-once contract, docs/perf.md).
        idxs = sorted(deleted_out)
        for b in partition_buckets([wnb_out[i] for i in idxs], bucket_bytes):
            bidx = [idxs[i] for i in b]
            back = jax.device_put(
                [host_out[i] for i in bidx], [shard_out[i] for i in bidx]
            )
            back = jax.block_until_ready(back)
            expanded = []
            for i, a in zip(bidx, back):
                if meta_out[i] is not None:
                    d = transfer_quant.dequantize_leaf(a, meta_out[i])
                    expanded.append((a, d))
                    leaves_out[i] = d
                else:
                    leaves_out[i] = a
            if expanded:
                jax.block_until_ready([d for _, d in expanded])
                for a, _ in expanded:
                    a.delete()
        if use_mk:
            # staging copies served their purpose (re-upload done): free
            # the pinned-host bytes
            for h in host_out:
                if h is not None:
                    h.delete()
        # the re-uploaded leaves are NEW arrays; the engine must point at
        # them (their originals are deleted)
        out_mgr._set_state(jax.tree.unflatten(treedef_out, leaves_out))
        if any(m is not None for m in meta_out):
            # a rolled-back FIRST quantized offload already rounded the
            # re-uploaded leaves: cache the scales it used, so the next
            # offload re-quantizes to the identical bits instead of
            # recomputing a perturbed scale from the rounded weights
            # (which could flip roundings — a second lossy step)
            out_mgr._quant_scales = [
                (m.scale if m is not None else None) for m in meta_out
            ]

    d2h_t0 = time.monotonic()
    try:
        for k in range(len(buckets_out)):
            cur = _issue_d2h(k)
            if pend_d2h is not None:
                _finish_d2h(pend_d2h)
                pend_d2h = None
                if overlapped and next_in < len(buckets_in):
                    if pend_h2d is not None:
                        _finish_h2d(pend_h2d)
                        pend_h2d = None
                    pend_h2d = _issue_h2d(next_in)
                    next_in += 1
            pend_d2h = cur
        if pend_d2h is not None:
            _finish_d2h(pend_d2h)
            pend_d2h = None
        d2h_t1 = time.monotonic()
        while next_in < len(buckets_in):
            if pend_h2d is not None:
                _finish_h2d(pend_h2d)
                pend_h2d = None
            pend_h2d = _issue_h2d(next_in)
            next_in += 1
        if pend_h2d is not None:
            _finish_h2d(pend_h2d)
            pend_h2d = None
    except Exception as exc:
        rb_sp = tracing.begin(
            "swap.rollback", parent=root_ctx,
            error=f"{type(exc).__name__}: {exc}",
        )
        try:
            _rollback()
        except Exception as rb_exc:
            rb_sp.set(rollback_failed=True)
            rb_sp.end()
            root.set(error="rollback_failed")
            root.end()
            raise SwapRollbackFailed(
                f"hot-swap transfer failed "
                f"({type(exc).__name__}: {exc}) and the rollback failed "
                f"({type(rb_exc).__name__}: {rb_exc}); device state is "
                "partially moved"
            ) from rb_exc
        rb_sp.end()
        root.set(error="rolled_back")
        root.end()
        raise SwapRolledBack(
            f"hot-swap transfer failed mid-flight; rolled back "
            f"({type(exc).__name__}: {exc})"
        ) from exc
    if in_payload_devs:
        # the last buckets' async dequants are part of the wake window:
        # land them, then free the device payload staging
        t_dq = time.monotonic()
        jax.block_until_ready([a for a in dev_in if a is not None])
        dq_bytes = sum(p.nbytes for p in in_payload_devs)
        for p in in_payload_devs:
            p.delete()
        # the non-hidden dequant tail: the quant-overhead EWMA kind
        out_mgr._notify_transfer(
            "quant.dequant", dq_bytes, time.monotonic() - t_dq
        )
    h2d_t1 = time.monotonic()
    if h2d_t0 is None:  # empty incoming tree (degenerate)
        h2d_t0 = h2d_t1
    if h2d_pool is not None:
        h2d_pool.shutdown(wait=True)  # no transfer outlives the swap
        for p in deferred_payload_frees:
            p.delete()
        for i in deferred_deletes:
            leaves_out[i].delete()
    if use_mk:
        # commit point for the incoming pool entry's pinned-host copies:
        # deferred from _finish_h2d so a rollback could re-pool it intact
        for i in deferred_in_frees:
            leaves_in[i].delete()

    # Delta handover, at commit only: each matched incoming leaf takes
    # over the outgoing model's live device array (content-identical by
    # digest), and the incoming host copy becomes the outgoing model's
    # slept host state — zero bytes crossed the device boundary for them.
    # A quantized incoming host copy carries its payload metadata along to
    # the outgoing model's slept state.
    for i, j in reuse_pairs:
        dev_in[i] = leaves_out[j]
        host_out[j] = leaves_in[i]
        meta_out[j] = in_metas[i]

    # Commit the state-machine edges: outgoing asleep (poolable host
    # state), incoming awake.
    out_mgr._host_state = jax.tree.unflatten(treedef_out, host_out)
    out_mgr._quant_meta = (
        meta_out if any(m is not None for m in meta_out) else None
    )
    out_mgr._shardings = jax.tree.unflatten(treedef_out, shard_out)
    out_mgr._sharding_specs = None
    out_mgr._staged = None
    out_mgr._set_state(None)
    out_mgr._level = SleepLevel.L1_HOST_OFFLOAD
    out_mgr.stats.last_sleep_seconds = d2h_t1 - d2h_t0
    out_mgr.stats.last_sleep_transfer_s = d2h_t1 - d2h_t0
    out_mgr.stats.bytes_offloaded = sum(
        x.nbytes for x in host_out if x is not None
    ) + sum(m.scale_nbytes for m in meta_out if m is not None)
    out_mgr.stats.bytes_offloaded_full = sum(nb_out)
    out_mgr.stats.last_quant = (
        quant_mode_used if out_mgr._quant_meta is not None else "off"
    )
    out_mgr.stats.sleeps_total += 1

    in_mgr._host_state = None
    in_mgr._shardings = None
    in_mgr._sharding_specs = None
    in_mgr._set_state(jax.tree.unflatten(treedef_in, dev_in))
    in_mgr._level = SleepLevel.AWAKE
    # scales cached for the incoming model's NEXT offload (bit-stable
    # re-quantization); payload metadata is consumed by this wake
    in_mgr._note_wake_quant(in_metas)
    in_mgr.stats.last_wake_seconds = h2d_t1 - h2d_t0
    in_mgr.stats.last_wake_transfer_s = h2d_t1 - h2d_t0
    in_mgr.stats.last_wake_bytes = bytes_in
    in_mgr.stats.bytes_offloaded = 0
    in_mgr.stats.bytes_offloaded_full = 0
    in_mgr.stats.wakes_total += 1

    total = time.monotonic() - t_begin
    # Overlap = intersection of the two directions' issue->complete
    # windows. Positive whenever an h2d was issued before the last d2h
    # completed — i.e. for any >= 2-bucket swap, by construction.
    overlap = max(0.0, min(d2h_t1, h2d_t1) - max(d2h_t0, h2d_t0))
    root.set(
        bytes_out=bytes_out,
        bytes_in=bytes_in,
        bytes_moved=moved_bytes,
        bytes_deduped=deduped_bytes,
        buckets_out=len(buckets_out),
        buckets_in=len(buckets_in),
        overlap_frac=round(overlap / total, 6) if total > 0 else 0.0,
        peak_bytes_in_flight=peak_in_flight,
    )
    root.end()
    # bandwidth EWMA feed (utils/costs.py): the two directions' measured
    # windows, over the bytes that actually crossed the boundary (totals
    # minus digest-matched leaves) — what pre-transfer pricing divides by
    out_mgr._notify_transfer(
        "swap.d2h",
        sum(wnb_out[i] for i in plan.move_out),
        d2h_t1 - d2h_t0,
    )
    out_mgr._notify_transfer(
        "swap.h2d",
        sum(wnb_in[i] for i in move_in),
        h2d_t1 - h2d_t0,
    )
    # effective whole-verb bandwidth (moved bytes over the full wall,
    # planning/staging/commit included): what pool-hit pricing prefers —
    # for repeated same-shape swaps it predicts the wall directly,
    # absorbing the fixed per-swap overhead the window EWMAs can't see
    out_mgr._notify_transfer("swap.total", moved_bytes, total)
    return {
        "swap_total_s": total,
        "d2h_s": d2h_t1 - d2h_t0,
        "h2d_s": h2d_t1 - h2d_t0,
        "overlap_s": overlap,
        "overlap_frac": overlap / total if total > 0 else 0.0,
        "bytes_out": bytes_out,
        "bytes_in": bytes_in,
        "bytes_moved": moved_bytes,
        "bytes_deduped": deduped_bytes,
        "deduped_leaves": len(reuse_pairs),
        # compressed-actuation accounting (docstring): wire vs full bytes
        "quant": quant_mode_used,
        "quant_leaves": quant_leaves,
        "bytes_full": bytes_full,
        "bytes_saved_quant": max(0, bytes_full - (bytes_out + bytes_in)),
        "buckets_out": len(buckets_out),
        "buckets_in": len(buckets_in),
        "bucket_bytes": bucket_bytes,
        "peak_bytes_in_flight": peak_in_flight,
    }


def attach_sleep(
    engine,
    bucket_bytes: Optional[int] = None,
    quant_mode: str = "off",
    quant_hot_head: bool = True,
    on_transfer: Optional[Callable[[str, int, float], None]] = None,
) -> SleepManager:
    """Wire a SleepManager to an InferenceEngine: the offloadable state is
    (params, kv page pool). Page tables / host bookkeeping stay put, so the
    wake fast path resumes in-flight sequences. Under zero-drain
    (``engine.kv_detached`` after a park) the state is weights-only — the
    live KV left compactly via engine/parked.py and the restore rebuilds a
    fresh pool for the bundle to scatter back into.

    ``quant_mode`` opts the level-1 offload path into compressed transfers
    (int8/fp8 payloads + on-device dequant; docs/perf.md "Compressed
    actuation"); ``quant_hot_head`` keeps embeddings / final norm /
    lm_head at full precision (the default). ``on_transfer`` feeds each
    completed transfer window's (kind, bytes, seconds) to the cost
    oracle's bandwidth EWMAs (utils/costs.py)."""

    def get_state():
        # a dispatched-but-unread decode chunk would be lost with the
        # device state: complete it (emitting its tokens) before offload
        engine.drain_inflight()
        if engine.kv_detached:
            # zero-drain park (engine/parked.py) already paged the live
            # KV out compactly and dropped the pool arrays: the slept
            # state is weights-only, and set_state rebuilds a fresh pool
            return {"params": engine.params}
        return {"params": engine.params, "kv": engine.pool.as_tuple()}

    def peek_state():
        # pricing reads shapes only: same tree, no quiesce. Under
        # zero-drain the L1 offload this prices will run AFTER a park,
        # so the peeked tree must exclude the pool too (the parked-KV
        # bytes are priced separately from parked_page_ids).
        if engine.kv_detached or engine.zero_drain_park:
            return {"params": engine.params}
        return {"params": engine.params, "kv": engine.pool.as_tuple()}

    def set_state(state):
        if state is None:
            engine.params = None
            engine.pool.k_pages = None
            engine.pool.v_pages = None
            # Scheduler arrays (tokens/positions/budgets/key) are device
            # state too — a sleeping engine must hold zero HBM. Host mirrors
            # stay authoritative; the first post-wake chunk re-uploads them.
            engine.drop_device_sched_state()
        else:
            engine.params = state["params"]
            if "kv" in state:
                engine.pool.replace(state["kv"])
            else:
                # weights-only state (zero-drain park): fresh pool +
                # allocator; the service re-seats the parked bundle next
                engine.rebuild_kv_pool()

    mgr = SleepManager(
        get_state,
        set_state,
        on_reacquire=engine.on_device_reacquire,
        bucket_bytes=bucket_bytes,
        quant_mode=quant_mode,
        quant_hot_head=quant_hot_head,
        on_transfer=on_transfer,
        peek_state=peek_state,
    )
    # back-reference for the co-resident precondition check in sleep():
    # the state closures above stage params+kv only, so attached variant
    # deltas must be detached before any offload
    mgr.engine = engine
    return mgr
