"""Tokenization for the OpenAI-facing server.

The reference's engine (vLLM) tokenizes text prompts with the model's own
Hugging Face tokenizer; this module gives our server the same behavior.
When the served model directory (or `--tokenizer`) carries tokenizer files,
text prompts, chat templates, stop strings, and response text all go
through the real tokenizer. Without one, the byte-level fallback keeps the
token-id API fully functional (tests, synthetic models).

Streaming uses `IncrementalDecoder`: decoding token-by-token is wrong for
SentencePiece/BPE (word-boundary markers, multi-byte codepoints split
across tokens), so deltas are computed as decode(all)[len(prev):], holding
back a trailing U+FFFD that marks an incomplete byte sequence.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional, Sequence


def _fallback_chat_text(messages: Sequence[Any]) -> str:
    """Role-tagged flattening for models without a chat template."""
    parts: List[str] = []
    for m in messages:
        parts.append(f"<|{m['role']}|>\n{m['content']}\n")
    parts.append("<|assistant|>\n")
    return "".join(parts)


class ByteTokenizer:
    """UTF-8 bytes as token ids — the no-tokenizer fallback."""

    eos_token_id: Optional[int] = None

    def encode(self, text: str, special: bool = True) -> List[int]:
        return list(text.encode("utf-8"))

    def decode(self, tokens: Sequence[int]) -> str:
        return bytes(t % 256 for t in tokens).decode(
            "utf-8", errors="replace"
        )

    def chat_tokens(self, messages: Sequence[Any]) -> List[int]:
        return self.encode(_fallback_chat_text(messages))


class HFTokenizer:
    """A Hugging Face tokenizer loaded from a LOCAL directory (the image
    has no network egress; models ship their tokenizers alongside the
    weights, exactly as vLLM consumes them)."""

    def __init__(self, path: str) -> None:
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(
            path, local_files_only=True
        )

    @property
    def eos_token_id(self) -> Optional[int]:
        return self._tok.eos_token_id

    def encode(self, text: str, special: bool = True) -> List[int]:
        return list(self._tok.encode(text, add_special_tokens=special))

    def decode(self, tokens: Sequence[int]) -> str:
        return self._tok.decode(list(tokens), skip_special_tokens=True)

    def chat_tokens(self, messages: Sequence[Any]) -> List[int]:
        if getattr(self._tok, "chat_template", None):
            return list(
                self._tok.apply_chat_template(
                    list(messages), add_generation_prompt=True
                )
            )
        return self.encode(_fallback_chat_text(messages))


#: files whose presence marks an HF tokenizer directory
_TOKENIZER_FILES = (
    "tokenizer.json",
    "tokenizer_config.json",
    "tokenizer.model",
    "vocab.json",
)


def has_tokenizer_files(path: str) -> bool:
    return any(
        os.path.isfile(os.path.join(path, f)) for f in _TOKENIZER_FILES
    )


def load_tokenizer(path: str = ""):
    """HFTokenizer for a directory path, ByteTokenizer for ''."""
    if path:
        return HFTokenizer(path)
    return ByteTokenizer()


class IncrementalDecoder:
    """Stream-safe detokenization: each push returns the NEW text the
    growing token sequence decodes to, never re-emitting and never
    emitting the replacement character for a not-yet-complete byte
    sequence (it flushes once the continuation tokens arrive).

    Cost is O(window) per push, not O(tokens-so-far): only the tokens
    since the last emission (plus a small already-emitted context window
    for tokenizers whose spacing depends on the previous token) are
    re-decoded — the prefix/read-offset scheme vLLM's incremental
    detokenizer uses."""

    def __init__(self, tokenizer) -> None:
        self._tok = tokenizer
        self._tokens: List[int] = []
        self._prefix = 0  # start of the decode context window
        self._read = 0  # tokens whose text has been emitted

    def push(self, token: int) -> str:
        self._tokens.append(int(token))
        ctx = self._tok.decode(self._tokens[self._prefix : self._read])
        full = self._tok.decode(self._tokens[self._prefix :])
        # a trailing U+FFFD marks a split multi-byte sequence: hold until
        # the continuation tokens arrive (flush releases a genuine one)
        if len(full) > len(ctx) and not full.endswith("�"):
            out = full[len(ctx) :]
            self._prefix = self._read
            self._read = len(self._tokens)
            return out
        return ""

    def flush(self) -> str:
        """Release any held tail (e.g. a trailing U+FFFD from a byte
        sequence the stream ended mid-way through) so streamed text equals
        the full decode exactly."""
        ctx = self._tok.decode(self._tokens[self._prefix : self._read])
        full = self._tok.decode(self._tokens[self._prefix :])
        self._read = len(self._tokens)
        return full[len(ctx) :]


class TextStopStream:
    """Streaming stop-STRING matching on decoded text (OpenAI semantics).

    String stops cannot be matched as token sequences: BPE does not
    round-trip decode→encode per token, and a stop string can start
    mid-token. This filter sits between the engine's token stream and the
    SSE writer: `push` returns (text_safe_to_emit, matched). Text that
    could be the start of a stop string is held back until disambiguated;
    on a match, everything before the stop is returned and the stream is
    over. `flush` releases held text when generation ends without a match.
    """

    def __init__(self, tokenizer, stop_texts) -> None:
        self._dec = IncrementalDecoder(tokenizer)
        self._stops = [s for s in stop_texts if s]
        self._pending = ""

    def push(self, token: int):
        self._pending += self._dec.push(token)
        cut = -1
        for s in self._stops:
            j = self._pending.find(s)
            if j >= 0 and (cut < 0 or j < cut):
                cut = j
        if cut >= 0:
            out = self._pending[:cut]
            self._pending = ""
            return out, True
        hold = 0
        for s in self._stops:
            m = min(len(s) - 1, len(self._pending))
            for k in range(m, hold, -1):
                if self._pending.endswith(s[:k]):
                    hold = k
                    break
        out = self._pending[: len(self._pending) - hold]
        self._pending = self._pending[len(out) :]
        return out, False

    def flush(self):
        """End-of-generation: release held text, SCANNING it for stops
        first — a stop string can hide in a tail the decoder was holding
        (split multi-byte sequence). Returns (text, matched)."""
        tail = self._pending + self._dec.flush()
        self._pending = ""
        cut = -1
        for s in self._stops:
            j = tail.find(s)
            if j >= 0 and (cut < 0 or j < cut):
                cut = j
        if cut >= 0:
            return tail[:cut], True
        return tail, False


def truncate_at_text_stop(tokenizer, tokens, logprobs, stop_texts):
    """Non-streaming stop-string application: cut the response at the
    first occurrence of any stop string in the decoded text.

    Returns (kept_tokens, kept_logprobs, text, matched). The token list is
    cut BEFORE the token whose arrival completed the match (a stop can
    start mid-token, so text is the authoritative boundary; the token list
    is the best id-aligned approximation).
    """
    tokens = list(tokens)
    if not stop_texts:
        return tokens, list(logprobs), tokenizer.decode(tokens), False
    dec = IncrementalDecoder(tokenizer)
    text = ""
    max_stop = max(len(s) for s in stop_texts)
    for i, t in enumerate(tokens):
        new = dec.push(t)
        text += new
        # a fresh match must involve newly-emitted chars: bound the scan
        start = max(0, len(text) - len(new) - max_stop)
        cut = -1
        for s in stop_texts:
            if not s:
                continue
            j = text.find(s, start)
            if j >= 0 and (cut < 0 or j < cut):
                cut = j
        if cut >= 0:
            return tokens[:i], list(logprobs)[:i], text[:cut], True
    # the decoder may have held a tail (split multi-byte sequence) that
    # push never scanned; a stop can hide in it
    text += dec.flush()
    start = max(0, len(text) - max_stop * 2)
    cut = -1
    for s in stop_texts:
        if not s:
            continue
        j = text.find(s, start)
        if j >= 0 and (cut < 0 or j < cut):
            cut = j
    if cut >= 0:
        return tokens, list(logprobs), text[:cut], True
    return tokens, list(logprobs), text, False
