"""Tokenization for the OpenAI-facing server.

The reference's engine (vLLM) tokenizes text prompts with the model's own
Hugging Face tokenizer; this module gives our server the same behavior.
When the served model directory (or `--tokenizer`) carries tokenizer files,
text prompts, chat templates, stop strings, and response text all go
through the real tokenizer. Without one, the byte-level fallback keeps the
token-id API fully functional (tests, synthetic models).

Streaming uses `IncrementalDecoder`: decoding token-by-token is wrong for
SentencePiece/BPE (word-boundary markers, multi-byte codepoints split
across tokens), so deltas are computed as decode(all)[len(prev):], holding
back a trailing U+FFFD that marks an incomplete byte sequence.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional, Sequence


def _fallback_chat_text(messages: Sequence[Any]) -> str:
    """Role-tagged flattening for models without a chat template."""
    parts: List[str] = []
    for m in messages:
        parts.append(f"<|{m['role']}|>\n{m['content']}\n")
    parts.append("<|assistant|>\n")
    return "".join(parts)


class ByteTokenizer:
    """UTF-8 bytes as token ids — the no-tokenizer fallback."""

    eos_token_id: Optional[int] = None

    def encode(self, text: str, special: bool = True) -> List[int]:
        return list(text.encode("utf-8"))

    def decode(self, tokens: Sequence[int], skip_special: bool = True) -> str:
        return bytes(t % 256 for t in tokens).decode(
            "utf-8", errors="replace"
        )

    def chat_tokens(self, messages: Sequence[Any]) -> List[int]:
        return self.encode(_fallback_chat_text(messages))


class HFTokenizer:
    """A Hugging Face tokenizer loaded from a LOCAL directory (the image
    has no network egress; models ship their tokenizers alongside the
    weights, exactly as vLLM consumes them)."""

    def __init__(self, path: str) -> None:
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(
            path, local_files_only=True
        )

    @property
    def eos_token_id(self) -> Optional[int]:
        return self._tok.eos_token_id

    def encode(self, text: str, special: bool = True) -> List[int]:
        return list(self._tok.encode(text, add_special_tokens=special))

    def decode(self, tokens: Sequence[int], skip_special: bool = True) -> str:
        """skip_special=True (streamed/assembled response text) hides
        BOS/EOS markers like vLLM's default detokenizer; callers that need
        the literal text — echo of the original prompt, single-token
        decodes for logprob alternative keys (distinct special ids must
        not all merge into '') — pass skip_special=False."""
        return self._tok.decode(
            list(tokens), skip_special_tokens=skip_special
        )

    def chat_tokens(self, messages: Sequence[Any]) -> List[int]:
        if getattr(self._tok, "chat_template", None):
            return list(
                self._tok.apply_chat_template(
                    list(messages), add_generation_prompt=True
                )
            )
        return self.encode(_fallback_chat_text(messages))


#: files whose presence marks an HF tokenizer directory
_TOKENIZER_FILES = (
    "tokenizer.json",
    "tokenizer_config.json",
    "tokenizer.model",
    "vocab.json",
)


def has_tokenizer_files(path: str) -> bool:
    return any(
        os.path.isfile(os.path.join(path, f)) for f in _TOKENIZER_FILES
    )


def load_tokenizer(path: str = ""):
    """HFTokenizer for a directory path, ByteTokenizer for ''."""
    if path:
        return HFTokenizer(path)
    return ByteTokenizer()


class IncrementalDecoder:
    """Stream-safe detokenization: each push returns the NEW text the
    growing token sequence decodes to, never re-emitting and never
    emitting the replacement character for a not-yet-complete byte
    sequence (it flushes once the continuation tokens arrive).

    Cost is O(window) per push, not O(tokens-so-far): only the tokens
    since the last emission (plus a small already-emitted context window
    for tokenizers whose spacing depends on the previous token) are
    re-decoded — the prefix/read-offset scheme vLLM's incremental
    detokenizer uses."""

    def __init__(self, tokenizer) -> None:
        self._tok = tokenizer
        self._tokens: List[int] = []
        self._prefix = 0  # start of the decode context window
        self._read = 0  # tokens whose text has been emitted

    def push(self, token: int) -> str:
        self._tokens.append(int(token))
        ctx = self._tok.decode(self._tokens[self._prefix : self._read])
        full = self._tok.decode(self._tokens[self._prefix :])
        # a trailing U+FFFD marks a split multi-byte sequence: hold until
        # the continuation tokens arrive (flush releases a genuine one)
        if len(full) > len(ctx) and not full.endswith("�"):
            out = full[len(ctx) :]
            self._prefix = self._read
            self._read = len(self._tokens)
            return out
        return ""

    def flush(self) -> str:
        """Release any held tail (e.g. a trailing U+FFFD from a byte
        sequence the stream ended mid-way through) so streamed text equals
        the full decode exactly."""
        ctx = self._tok.decode(self._tokens[self._prefix : self._read])
        full = self._tok.decode(self._tokens[self._prefix :])
        self._read = len(self._tokens)
        return full[len(ctx) :]


class TextStopStream:
    """Streaming stop-STRING matching on decoded text (OpenAI semantics).

    String stops cannot be matched as token sequences: BPE does not
    round-trip decode→encode per token, and a stop string can start
    mid-token. This filter sits between the engine's token stream and the
    SSE writer: `push` returns (text_safe_to_emit, ids, matched). Text
    that could be the start of a stop string is held back until
    disambiguated; on a match, everything before the stop is returned and
    the stream is over. `flush` releases held text when generation ends
    without a match.

    `ids` are the token ids whose decoded text is FULLY contained in the
    returned text, so streamed ids account for exactly the delivered text
    at token granularity: each pushed token's chars are tracked through
    the hold-back window, a token is delivered with the emission that
    completes its text, and a token straddling a stop cut is suppressed
    with the stop (the cut-before-the-matching-token rule of
    truncate_at_text_stop)."""

    def __init__(self, tokenizer, stop_texts) -> None:
        self._dec = IncrementalDecoder(tokenizer)
        self._stops = [s for s in stop_texts if s]
        self._pending = ""
        #: [token id, chars of _pending attributed to it] in arrival order;
        #: invariant: sum of chars == len(_pending)
        self._idq: List[list] = []

    def _take_ids(self, k: int) -> List[int]:
        """Pop the ids whose attributed chars lie within the first `k`
        chars of the pending window (a token partially inside stays
        queued, its remaining char count reduced)."""
        out: List[int] = []
        while self._idq and k >= 0:
            tid, n = self._idq[0]
            if n <= k:
                k -= n
                out.append(tid)
                self._idq.pop(0)
                if k == 0:
                    break
            else:
                self._idq[0][1] = n - k
                break
        return out

    def push(self, token: int):
        new = self._dec.push(token)
        self._pending += new
        self._idq.append([int(token), len(new)])
        cut = -1
        for s in self._stops:
            j = self._pending.find(s)
            if j >= 0 and (cut < 0 or j < cut):
                cut = j
        if cut >= 0:
            out = self._pending[:cut]
            ids = self._take_ids(cut) if cut else []
            self._pending = ""
            self._idq = []
            return out, ids, True
        hold = 0
        for s in self._stops:
            m = min(len(s) - 1, len(self._pending))
            for k in range(m, hold, -1):
                if self._pending.endswith(s[:k]):
                    hold = k
                    break
        out = self._pending[: len(self._pending) - hold]
        self._pending = self._pending[len(out) :]
        return out, self._take_ids(len(out)) if out else [], False

    def flush(self):
        """End-of-generation: release held text, SCANNING it for stops
        first — a stop string can hide in a tail the decoder was holding
        (split multi-byte sequence). Returns (text, ids, matched)."""
        tail_new = self._dec.flush()
        if tail_new and self._idq:
            # decoder-held chars surfaced now; they came from the queued
            # tokens — attribute to the newest (greedy, same as push)
            self._idq[-1][1] += len(tail_new)
        tail = self._pending + tail_new
        self._pending = ""
        cut = -1
        for s in self._stops:
            j = tail.find(s)
            if j >= 0 and (cut < 0 or j < cut):
                cut = j
        if cut >= 0:
            ids = self._take_ids(cut) if cut else []
            self._idq = []
            return tail[:cut], ids, True
        ids = [tid for tid, _ in self._idq]
        self._idq = []
        return tail, ids, False


def truncate_at_text_stop(tokenizer, tokens, logprobs, stop_texts):
    """Non-streaming stop-string application: cut the response at the
    first occurrence of any stop string in the decoded text.

    Returns (kept_tokens, kept_logprobs, text, matched). The token list is
    cut BEFORE the token whose arrival completed the match (a stop can
    start mid-token, so text is the authoritative boundary; the token list
    is the best id-aligned approximation).
    """
    tokens = list(tokens)
    if not stop_texts:
        return tokens, list(logprobs), tokenizer.decode(tokens), False
    dec = IncrementalDecoder(tokenizer)
    text = ""
    max_stop = max(len(s) for s in stop_texts)
    for i, t in enumerate(tokens):
        new = dec.push(t)
        text += new
        # a fresh match must involve newly-emitted chars: bound the scan
        start = max(0, len(text) - len(new) - max_stop)
        cut = -1
        for s in stop_texts:
            if not s:
                continue
            j = text.find(s, start)
            if j >= 0 and (cut < 0 or j < cut):
                cut = j
        if cut >= 0:
            return tokens[:i], list(logprobs)[:i], text[:cut], True
    # the decoder may have held a tail (split multi-byte sequence) that
    # push never scanned; a stop can hide in it
    text += dec.flush()
    start = max(0, len(text) - max_stop * 2)
    cut = -1
    for s in stop_texts:
        if not s:
            continue
        j = text.find(s, start)
        if j >= 0 and (cut < 0 or j < cut):
            cut = j
    if cut >= 0:
        return tokens, list(logprobs), text[:cut], True
    return tokens, list(logprobs), text, False
