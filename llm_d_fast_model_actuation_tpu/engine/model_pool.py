"""Host-side model pool: LRU registry of slept model runtimes.

The hot-swap path (docs/engine.md "Model hot-swap") lets N models time-share
one chip: the model being swapped out goes to sleep (level 1, host-resident
state) and is *pooled* here instead of discarded, keyed by model id and
bounded by a pinned-host byte budget. A later swap back is then a pure
host->HBM restore — no checkpoint re-read, no recompile (the runtime keeps
its compiled programs, which are host-resident and survive sleep).

The pool stores opaque runtime entries (the engine server's model-runtime
bundle); the only contract is that an evicted entry's host bytes are freed
by the caller (the server escalates the evicted sleeper to level 2). LRU
order is by swap-out recency: the model least recently *parked* is the
first to lose its host residency under budget pressure — mirroring the
multi-model scheduler policy in "Towards Multi-Model LLM Schedulers"
(PAPERS.md) where victim selection is recency-driven.

Mutations happen under the engine server's step lock, but observability
reads (/metrics) come from other threads — an internal mutex makes every
operation safe to call concurrently.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class PoolEntry:
    model_id: str
    runtime: Any  #: opaque bundle (engine + sleeper + tokenizer + ...)
    nbytes: int  #: pinned-host bytes the slept state occupies
    stored_at: float = field(default_factory=time.monotonic)


class HostModelPool:
    """LRU-evicted registry of slept models under a host byte budget.

    ``budget_bytes <= 0`` disables pooling: every ``put`` immediately
    returns its own entry as evicted, so the caller frees it and the next
    swap-in is a cold build — the same code path, just with a zero cache.
    """

    def __init__(self, budget_bytes: int = 0) -> None:
        self.budget_bytes = int(budget_bytes)
        self._mu = threading.Lock()
        self._entries: "OrderedDict[str, PoolEntry]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, model_id: str) -> bool:
        return model_id in self._entries

    @property
    def bytes_used(self) -> int:
        with self._mu:
            return sum(e.nbytes for e in self._entries.values())

    def models(self) -> List[str]:
        """Pooled model ids, LRU first."""
        with self._mu:
            return list(self._entries)

    def take(self, model_id: str) -> Optional[PoolEntry]:
        """Remove and return the entry for ``model_id`` (a pool hit — the
        caller wakes it, so it leaves the pool), or None (miss)."""
        with self._mu:
            entry = self._entries.pop(model_id, None)
            if entry is None:
                self.misses += 1
            else:
                self.hits += 1
            return entry

    def contains_match(self, model_id: str) -> bool:
        """Non-mutating ``take_match`` probe: is anything pooled under this
        model name, with or without a checkpoint qualifier? (Used by
        prefetch to skip re-staging an already-resident model; counts no
        hit/miss.)"""
        with self._mu:
            return any(
                key == model_id or key.startswith(model_id + "@")
                for key in self._entries
            )

    def take_match(self, model_id: str) -> Optional[PoolEntry]:
        """Remove and return the most-recently-parked entry pooled under
        this model name regardless of checkpoint qualifier (keys are
        ``name`` or ``name@checkpoint_dir``): a swap request that omits
        checkpoint_dir means "this model, whatever source it came from"."""
        with self._mu:
            for key in reversed(self._entries):
                if key == model_id or key.startswith(model_id + "@"):
                    self.hits += 1
                    return self._entries.pop(key)
            self.misses += 1
            return None

    def put(self, model_id: str, runtime: Any, nbytes: int) -> List[PoolEntry]:
        """Register a just-slept model as most-recently-used and evict LRU
        entries until the byte budget holds. Returns the evicted entries
        (possibly including the new one, when it alone exceeds the budget
        or pooling is disabled); the caller must free their host state."""
        entry = PoolEntry(model_id=model_id, runtime=runtime, nbytes=int(nbytes))
        with self._mu:
            # replacing an id re-registers it as most recent
            old = self._entries.pop(model_id, None)
            evicted: List[PoolEntry] = [old] if old is not None else []
            if entry.nbytes > self.budget_bytes:
                # the newcomer alone can never fit: evict IT, not the
                # resident models that still can be hit
                self.evictions += 1 + len(evicted)
                return evicted + [entry]
            self._entries[model_id] = entry
            while (
                sum(e.nbytes for e in self._entries.values())
                > self.budget_bytes
            ):
                _, victim = self._entries.popitem(last=False)
                evicted.append(victim)
                self.evictions += 1
            return evicted

    def drain(self) -> List[PoolEntry]:
        """Remove and return every entry (counted as evictions): the caller
        is invalidating the pool wholesale — e.g. a device-releasing sleep
        is about to destroy the client that owns the pooled states' pinned
        host buffers and compiled programs."""
        with self._mu:
            out = list(self._entries.values())
            self._entries.clear()
            self.evictions += len(out)
            return out

    def describe(self) -> Dict[str, Any]:
        return {
            "models": self.models(),
            "bytes_used": self.bytes_used,
            "budget_bytes": self.budget_bytes,
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
        }
