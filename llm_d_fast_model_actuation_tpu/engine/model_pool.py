"""Tiered host-side model pool: LRU registry of slept/staged models over a
content-addressed chunk store.

The hot-swap path (docs/engine.md "Model hot-swap") lets N models time-share
one chip: the model being swapped out goes to sleep (level 1, host-resident
state) and is *pooled* here instead of discarded, keyed by model id and
bounded by a host byte budget. A later swap back is then a pure host->HBM
restore — no checkpoint re-read, no recompile.

Since the tiered rebuild (docs/perf.md "Tiered weight cache and delta
swap") the pool is two tiers deep and content-addressed:

  * **Host DRAM (hot tier)** — pooled entries whose weight leaves carry
    content digests are *interned* into a :class:`~.chunk_store.ChunkStore`:
    two fine-tunes of one base model hold their common tensors in host
    memory exactly once (refcounted), and ``bytes_used`` is the real
    deduped residency, maintained as a RUNNING counter (no O(n) re-sum per
    eviction step or per /metrics scrape).
  * **Local disk (spill tier)** — an evicted entry leaves behind a
    *manifest* (flat key -> digest) while its last-reference chunks spill
    to disk (atomic rename, content-verified reload). A later swap to the
    evicted model reconstructs its weights from the tiers
    (``take_staged``) — local SSD instead of a network checkpoint re-read;
    any unresolvable chunk makes the whole reconstruction a miss.

The pool stores opaque runtime entries (the engine server's model-runtime
bundle); the only contract is that an evicted entry's host bytes are freed
by the caller (the server escalates the evicted sleeper to level 2). LRU
order is by swap-out recency — mirroring the recency-driven victim
selection in "Towards Multi-Model LLM Schedulers" (PAPERS.md); tier
placement follows 10Cache's cost-aware migration (PAPERS.md).

Mutations happen under the engine server's step lock, but observability
reads (/metrics) come from other threads — an internal mutex makes every
operation safe to call concurrently.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .chunk_store import ChunkStore, aligned_digests, unflatten_tree

#: ceiling on remembered manifests of evicted entries: each is a small
#: dict of digests, but an unbounded registry would grow with every model
#: ever served
MAX_MANIFESTS = 64


@dataclass
class PoolEntry:
    model_id: str
    runtime: Any  #: opaque bundle (engine + sleeper + tokenizer + ...)
    nbytes: int  #: nominal host bytes the slept state occupies (pre-dedup)
    stored_at: float = field(default_factory=time.monotonic)
    #: digests whose chunk-store references this entry holds (interned)
    chunk_digests: List[str] = field(default_factory=list)
    #: flat weight key -> digest: the manifest an eviction leaves behind
    weight_digests: Optional[Dict[str, str]] = None
    #: bytes this entry adds OUTSIDE the chunk store (non-digested leaves
    #: — KV pages, scheduler state — plus everything when not interned)
    resident_bytes: int = 0


class HostModelPool:
    """Tiered LRU registry of slept models under a host byte budget.

    ``budget_bytes <= 0`` disables pooling: every ``put`` immediately
    returns its own entry as evicted, so the caller frees it and the next
    swap-in is a cold build — the same code path, just with a zero cache.

    ``chunks`` (a ChunkStore) enables the content-addressed tiers; without
    it the pool behaves exactly like the pre-tier flat LRU.
    """

    def __init__(
        self, budget_bytes: int = 0, chunks: Optional[ChunkStore] = None
    ) -> None:
        self.budget_bytes = int(budget_bytes)
        self.chunks = chunks
        self._mu = threading.Lock()
        self._entries: "OrderedDict[str, PoolEntry]" = OrderedDict()
        #: manifests of evicted entries whose chunks may still be
        #: resolvable from the tiers: key -> (weight_digests, nbytes)
        self._manifests: "OrderedDict[str, Tuple[Dict[str, str], int]]" = (
            OrderedDict()
        )
        #: running non-interned residency — with the chunk store's own
        #: running host_bytes this makes bytes_used O(1) (the flat pool
        #: re-summed every entry per eviction victim AND per scrape)
        self._resident_bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.staged_hits = 0
        self.staged_misses = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, model_id: str) -> bool:
        return model_id in self._entries

    @property
    def bytes_used(self) -> int:
        """Actual (deduped) host residency: running counters only."""
        base = self._resident_bytes
        if self.chunks is not None:
            base += self.chunks.host_bytes
        return base

    def models(self) -> List[str]:
        """Pooled model ids, LRU first."""
        with self._mu:
            return list(self._entries)

    # -- interning ------------------------------------------------------------

    def intern_tree(
        self,
        tree: Any,
        digests: Optional[Dict[str, str]],
        prefix: str = "params",
    ) -> Tuple[Any, List[str], int]:
        """Replace digested numpy leaves of ``tree`` with canonical
        chunk-store arrays (dedup across pooled variants). Returns
        ``(interned_tree, held_digests, interned_nominal_bytes)`` — the
        caller passes the latter two to :meth:`put`. A disabled store (or
        no digests) returns the tree untouched.

        Only plain numpy leaves intern: pinned-host jax arrays (TPU sleep
        staging) are client-owned and cannot be shared across trees, so
        they keep per-entry residency (documented in docs/perf.md).
        Transfer-quantized payloads intern under ``"q:"`` digests and
        spill to disk like any other chunk — the spill header's content
        hash makes the reload verifiable (chunk_store._load_spilled)."""
        if self.chunks is None or not digests or self.budget_bytes <= 0:
            return tree, [], 0
        import numpy as np
        from jax.tree_util import tree_flatten, tree_unflatten

        leaves, treedef = tree_flatten(tree)
        dlist = aligned_digests(tree, digests, prefix=prefix)
        held: List[str] = []
        nominal = 0
        out = list(leaves)
        for i, (leaf, d) in enumerate(zip(leaves, dlist)):
            if d is None or not isinstance(leaf, np.ndarray):
                continue
            canonical, _added = self.chunks.intern(d, leaf)
            out[i] = canonical
            held.append(d)
            nominal += int(leaf.nbytes)
        return tree_unflatten(treedef, out), held, nominal

    def _release_refs(self, entry: PoolEntry, spill: bool) -> None:
        if self.chunks is None:
            return
        for d in entry.chunk_digests:
            self.chunks.release(d, spill=spill)
        entry.chunk_digests = []

    def _record_manifest(self, entry: PoolEntry) -> None:
        if (
            self.chunks is None
            or not entry.weight_digests
            or self.budget_bytes <= 0
        ):
            return
        self._manifests.pop(entry.model_id, None)
        self._manifests[entry.model_id] = (
            dict(entry.weight_digests),
            entry.nbytes,
        )
        while len(self._manifests) > MAX_MANIFESTS:
            self._manifests.popitem(last=False)

    # -- take / put -----------------------------------------------------------

    def peek(self, model_id: str) -> Optional[PoolEntry]:
        """Non-consuming :meth:`take`: the entry stays pooled, LRU order
        and hit/miss counters untouched. The cost oracle prices pooled
        candidates through this — pricing must never change pool state.
        The returned entry is live and may be taken by a concurrent
        swap; callers treat it as an advisory snapshot."""
        with self._mu:
            return self._entries.get(model_id)

    def peek_match(self, model_id: str) -> Optional[PoolEntry]:
        """Non-consuming :meth:`take_match` (same key-or-qualified rule,
        most recently parked first)."""
        with self._mu:
            for key in reversed(self._entries):
                if key == model_id or key.startswith(model_id + "@"):
                    return self._entries[key]
        return None

    def peek_staged(self, key: str) -> Optional[Tuple[int, str, int]]:
        """Non-consuming tier probe of an evicted model's manifest:
        ``(nbytes, tier, chunks)`` where tier is ``"host"`` (every chunk
        still DRAM-resident via a sibling's references) or ``"disk"`` (at
        least one chunk would need a verified disk reload), or None when
        there is no manifest or any chunk is a miss on both tiers (a
        rebuild would fall through to a cold load). Unlike
        :meth:`take_staged` this never pops the manifest, reads no file,
        and rebuilds nothing — the cost oracle's pre-transfer pricing."""
        with self._mu:
            manifest = self._manifests.get(key)
        if manifest is None or self.chunks is None:
            return None
        digests, nbytes = manifest
        tier = "host"
        for d in digests.values():
            t = self.chunks.peek_tier(d)
            if t is None:
                return None
            if t == "disk":
                tier = "disk"
        return int(nbytes), tier, len(digests)

    def peek_staged_match(
        self, model_id: str
    ) -> Optional[Tuple[str, int, str, int]]:
        """:meth:`peek_staged` under any checkpoint qualifier (most
        recently evicted first); returns (key, nbytes, tier, chunks)."""
        with self._mu:
            keys = [
                k
                for k in reversed(self._manifests)
                if k == model_id or k.startswith(model_id + "@")
            ]
        for k in keys:
            got = self.peek_staged(k)
            if got is not None:
                return k, got[0], got[1], got[2]
        return None

    def take(self, model_id: str) -> Optional[PoolEntry]:
        """Remove and return the entry for ``model_id`` (a pool hit — the
        caller wakes it, so it leaves the pool), or None (miss)."""
        with self._mu:
            entry = self._entries.pop(model_id, None)
            if entry is None:
                self.misses += 1
                return None
            self.hits += 1
            self._resident_bytes -= entry.resident_bytes
        # no spill: the model is about to go live; its weights come back
        # at the next swap-out (and sibling-shared chunks keep their refs)
        self._release_refs(entry, spill=False)
        return entry

    def contains_match(self, model_id: str) -> bool:
        """Non-mutating ``take_match`` probe: is anything pooled under this
        model name, with or without a checkpoint qualifier? (Used by
        prefetch to skip re-staging an already-resident model; counts no
        hit/miss.)"""
        with self._mu:
            return any(
                key == model_id or key.startswith(model_id + "@")
                for key in self._entries
            )

    def take_match(self, model_id: str) -> Optional[PoolEntry]:
        """Remove and return the most-recently-parked entry pooled under
        this model name regardless of checkpoint qualifier (keys are
        ``name`` or ``name@checkpoint_dir``): a swap request that omits
        checkpoint_dir means "this model, whatever source it came from"."""
        with self._mu:
            found = None
            for key in reversed(self._entries):
                if key == model_id or key.startswith(model_id + "@"):
                    found = key
                    break
            if found is None:
                self.misses += 1
                return None
            self.hits += 1
            entry = self._entries.pop(found)
            self._resident_bytes -= entry.resident_bytes
        self._release_refs(entry, spill=False)
        return entry

    def put(
        self,
        model_id: str,
        runtime: Any,
        nbytes: int,
        chunk_digests: Optional[List[str]] = None,
        weight_digests: Optional[Dict[str, str]] = None,
        interned_bytes: int = 0,
    ) -> List[PoolEntry]:
        """Register a just-slept model as most-recently-used and evict LRU
        entries until the byte budget holds. Returns the evicted entries
        (possibly including the new one, when it alone exceeds the budget
        or pooling is disabled); the caller must free their host state.

        ``chunk_digests``/``interned_bytes`` come from :meth:`intern_tree`
        (the entry's weight leaves already point at canonical chunk-store
        arrays); ``weight_digests`` is the flat manifest an eviction
        records so the disk tier can later rebuild this model."""
        entry = PoolEntry(
            model_id=model_id,
            runtime=runtime,
            nbytes=int(nbytes),
            chunk_digests=list(chunk_digests or []),
            weight_digests=weight_digests,
            resident_bytes=max(0, int(nbytes) - int(interned_bytes)),
        )
        evicted: List[PoolEntry] = []
        bounced: Optional[List[PoolEntry]] = None
        spills: List[Tuple[str, Any]] = []
        with self._mu:
            # replacing an id re-registers it as most recent
            old = self._entries.pop(model_id, None)
            if old is not None:
                self._resident_bytes -= old.resident_bytes
                # a same-id replace drops the old entry's chunk refs
                # without spilling: the new entry just re-interned the
                # same content
                self._release_refs(old, spill=False)
                evicted.append(old)
            if entry.nbytes > self.budget_bytes:
                # the newcomer alone can never fit: evict IT, not the
                # resident models that still can be hit
                self.evictions += 1 + len(evicted)
                bounced = evicted + [entry]
            else:
                self._entries[model_id] = entry
                self._resident_bytes += entry.resident_bytes
                while self.bytes_used > self.budget_bytes:
                    _, victim = self._entries.popitem(last=False)
                    self._resident_bytes -= victim.resident_bytes
                    # refs drop under the lock (keeps bytes_used coherent
                    # with the loop condition) but the spill's DISK I/O is
                    # deferred past it: a multi-GiB victim's write must
                    # not block every other pool op on this mutex
                    if self.chunks is not None:
                        for d in victim.chunk_digests:
                            freed = self.chunks.release_deferred(d)
                            if freed is not None:
                                spills.append(freed)
                        victim.chunk_digests = []
                    self._record_manifest(victim)
                    evicted.append(victim)
                    self.evictions += 1
        if bounced is None:
            for d, data in spills:
                self.chunks.spill(d, data)
            return evicted
        # bounce path (pool disabled / oversize): refs released outside
        # the lock; the spill keeps the weights reachable via the manifest
        for e in bounced:
            self._release_refs(e, spill=True)
            with self._mu:
                self._record_manifest(e)
        return bounced

    def drain(self) -> List[PoolEntry]:
        """Remove and return every entry (counted as evictions): the caller
        is invalidating the pool wholesale — e.g. a device-releasing sleep
        is about to destroy the client that owns the pooled states' pinned
        host buffers and compiled programs. Chunked numpy weights are NOT
        client-owned: they spill to the disk tier and stay reconstructable
        through their manifests."""
        with self._mu:
            out = list(self._entries.values())
            self._entries.clear()
            self._resident_bytes = 0
            self.evictions += len(out)
        for entry in out:
            self._release_refs(entry, spill=True)
            with self._mu:
                self._record_manifest(entry)
        return out

    # -- the spill tier: manifest reconstruction ------------------------------

    def staged_keys(self) -> List[str]:
        with self._mu:
            return list(self._manifests)

    def staged_manifest(self, key: str) -> Optional[Dict[str, str]]:
        """Non-consuming copy of an evicted model's flat digest manifest
        (key -> digest), or None. The co-resident attach path diffs this
        against the live base's digests WITHOUT popping the manifest —
        the variant stays tier-rebuildable for a later full swap."""
        with self._mu:
            got = self._manifests.get(key)
            return dict(got[0]) if got is not None else None

    def staged_manifest_match(
        self, model_id: str
    ) -> Optional[Tuple[str, Dict[str, str]]]:
        """:meth:`staged_manifest` under any checkpoint qualifier (most
        recently evicted first); returns (matched_key, manifest)."""
        with self._mu:
            for k in reversed(self._manifests):
                if k == model_id or k.startswith(model_id + "@"):
                    return k, dict(self._manifests[k][0])
        return None

    def take_staged(
        self, key: str
    ) -> Optional[Tuple[Dict[str, Any], Dict[str, str], str]]:
        """Rebuild an evicted model's host weight tree from the tiers.
        Returns ``(params_tree, weight_digests, tier)`` — tier ``"host"``
        when every chunk was still host-resident via a sibling's live
        references, ``"disk"`` when any verified disk reload was needed —
        or None: any unresolvable chunk is a miss for the WHOLE model (a
        partial tree must never serve), and drops the stale manifest.
        Disk fetches (read + content re-hash) run on a small thread pool:
        the rebuild sits on the swap critical path, and serial hash-bound
        reloads of a multi-GiB model would undo the tier's win over the
        parallel cold loader."""
        with self._mu:
            manifest = self._manifests.pop(key, None)
        if manifest is None or self.chunks is None:
            return None
        digests, _nbytes = manifest
        items = list(digests.items())
        from_disk = any(d not in self.chunks for _, d in items)
        workers = min(8, os.cpu_count() or 1, max(1, len(items)))
        if workers > 1 and from_disk:
            with ThreadPoolExecutor(
                workers, thread_name_prefix="pool-tier-fetch"
            ) as ex:
                arrs = list(
                    ex.map(lambda kv: self.chunks.fetch(kv[1]), items)
                )
        else:
            arrs = [self.chunks.fetch(d) for _, d in items]
        if any(a is None for a in arrs):
            with self._mu:
                self.staged_misses += 1
            return None
        flat = {k: a for (k, _), a in zip(items, arrs)}
        with self._mu:
            self.staged_hits += 1
        return (
            unflatten_tree(flat),
            dict(digests),
            "disk" if from_disk else "host",
        )

    def take_staged_match(
        self, model_id: str
    ) -> Optional[Tuple[Dict[str, Any], Dict[str, str], str, str]]:
        """``take_staged`` under any checkpoint qualifier (most recently
        evicted first); returns (tree, digests, matched_key, tier)."""
        with self._mu:
            keys = [
                k
                for k in reversed(self._manifests)
                if k == model_id or k.startswith(model_id + "@")
            ]
        for k in keys:
            got = self.take_staged(k)
            if got is not None:
                return got[0], got[1], k, got[2]
        return None

    def describe(self) -> Dict[str, Any]:
        with self._mu:
            entries = [
                {
                    "model_id": e.model_id,
                    "nbytes": e.nbytes,
                    "resident_bytes": e.resident_bytes,
                }
                for e in self._entries.values()
            ]
            manifests = list(self._manifests)
        out = {
            "models": self.models(),
            "bytes_used": self.bytes_used,
            "budget_bytes": self.budget_bytes,
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "entries": entries,
            "staged_manifests": manifests,
            "staged_hits": self.staged_hits,
            "staged_misses": self.staged_misses,
        }
        if self.chunks is not None:
            out["chunks"] = self.chunks.describe()
        return out


class ResidentSetLedger:
    """Device-tier refcounts for co-resident sibling variants
    (docs/perf.md "Co-resident sibling variants").

    The engine holds one device copy of every base leaf plus per-variant
    delta leaves; this ledger mirrors that sharing on the host side so
    observability can answer the acceptance question directly: how many
    device bytes do N co-resident siblings occupy vs N full copies?

    ``attach(model, shared, deltas)`` records a variant whose digest diff
    against the live base splits its leaves into ``shared`` (digest ->
    nbytes held by the base tensor, device bytes NOT re-paid) and
    ``deltas`` (digest -> nbytes of the variant-private device leaf).
    Refcounts let two attached variants share an identical delta leaf in
    the accounting even though today's engine uploads each delta
    privately — the ledger reports what dedup *saves*, not what a
    hypothetical further dedup could save (``bytes_if_duplicated`` minus
    ``bytes_device``).

    Thread-safe: attach/detach run under the engine server's step lock,
    but /metrics and /v1/stats read from other threads.
    """

    def __init__(self) -> None:
        self._mu = threading.Lock()
        #: digest -> [refs, nbytes] across base-shared leaves
        self._shared: Dict[str, List[int]] = {}
        #: model_id -> (shared_digests {d: nbytes}, delta_digests {d: nbytes})
        self._members: Dict[str, Tuple[Dict[str, int], Dict[str, int]]] = {}

    def attach(
        self,
        model_id: str,
        shared: Dict[str, int],
        deltas: Dict[str, int],
    ) -> None:
        with self._mu:
            self._members.pop(model_id, None)
            self._members[model_id] = (dict(shared), dict(deltas))
            for d, n in shared.items():
                ref = self._shared.get(d)
                if ref is None:
                    self._shared[d] = [1, int(n)]
                else:
                    ref[0] += 1

    def detach(self, model_id: str) -> None:
        with self._mu:
            got = self._members.pop(model_id, None)
            if got is None:
                return
            shared, _deltas = got
            for d in shared:
                ref = self._shared.get(d)
                if ref is None:
                    continue
                ref[0] -= 1
                if ref[0] <= 0:
                    del self._shared[d]

    def members(self) -> List[str]:
        with self._mu:
            return list(self._members)

    def bytes_device(self) -> int:
        """Actual variant device bytes: per-variant delta leaves only —
        shared base leaves are the live engine's own tensors, already
        counted in its residency, never re-paid per variant."""
        with self._mu:
            return sum(
                sum(deltas.values())
                for _shared, deltas in self._members.values()
            )

    def bytes_if_duplicated(self) -> int:
        """What the same resident set would cost as full per-variant
        copies: every member's shared + delta bytes, no dedup."""
        with self._mu:
            return sum(
                sum(shared.values()) + sum(deltas.values())
                for shared, deltas in self._members.values()
            )

    def bytes_saved(self) -> int:
        """Device bytes co-residency avoids re-paying (the saved-bytes
        gauge): duplicated-cost minus actual delta residency."""
        return max(0, self.bytes_if_duplicated() - self.bytes_device())

    def describe(self) -> Dict[str, Any]:
        with self._mu:
            members = {
                m: {
                    "shared_bytes": sum(shared.values()),
                    "delta_bytes": sum(deltas.values()),
                    "shared_leaves": len(shared),
                    "delta_leaves": len(deltas),
                }
                for m, (shared, deltas) in self._members.items()
            }
        return {
            "members": members,
            "bytes_device": self.bytes_device(),
            "bytes_if_duplicated": self.bytes_if_duplicated(),
            "bytes_saved": self.bytes_saved(),
        }
