"""Zero-drain actuation: live request state, paged out like weights.

Today an actuation and the requests it preempts are mutually exclusive:
a swap aborts every queued and in-flight request of the outgoing model.
The paged KV cache makes request state chunkable exactly the way weights
are — a request's KV lives in whole pages, its scheduler state in small
per-slot host rows — so the transactional sleep/swap discipline extends
to requests: **park** them (page the live KV pages to host, capture the
per-slot scheduler rows and RNG key state), store the bundle alongside
the slept weights in the model pool, and **resume** them bit-exact after
the wake/swap-back (page the KV back in, re-seat page tables and slots).

This module holds the data shapes and the two transfer primitives; the
park/resume *orchestration* lives on :class:`~.engine.InferenceEngine`
(it owns the scheduler state being detached/re-seated) and the service
wires it into the swap/sleep verbs behind ``--zero-drain``
(engine/server.py).

Transfer discipline matches engine/sleep.py: size-bounded chunks (whole
pages, never split), each chunk landed before the next is issued, with
named fault-injection points (``kvsave.d2h`` on page-out,
``kvrestore.h2d`` on page-in — utils/faults.py) so the failure paths are
deterministically drillable. A page-out failure leaves the engine
untouched (the caller falls back to the abort path); a page-in failure
is rolled back to a *clean* abort of the parked requests with the
existing ``state_loss`` cause — never a wedged slot or a corrupted page
table.
"""

from __future__ import annotations

import base64
import hashlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import faults, tracing

#: chunk bound fallback when the caller passes none: matches the swap
#: bucket default (engine/sleep.py DEFAULT_SWAP_BUCKET_BYTES)
DEFAULT_KV_CHUNK_BYTES = 256 << 20

#: parked-bundle wire format version (GET/POST /v1/parked): bumped on
#: any incompatible change so a mixed-version fleet rejects the handoff
#: instead of mis-seating state
WIRE_VERSION = 1


class ParkedResumeFailed(RuntimeError):
    """A zero-drain resume failed mid page-in and was rolled back: no
    slot was seated, every allocated page was returned, and the engine
    is healthy with an empty (fresh) KV pool. The parked requests' KV is
    unrecoverable — the caller aborts them with cause ``state_loss``."""


@dataclass
class ParkedRequest:
    """One preempted mid-generation request: the pure-host Request
    object plus the device-derived state a bit-exact resume needs."""

    req: Any  #: engine.Request — prompt, emitted tokens, sampling knobs
    #: pool page ids (old pool) holding this request's live KV, page-table
    #: order — the first ``ceil(pos / page_size)`` of its allocation
    old_pages: List[int] = field(default_factory=list)
    #: [vocab] int32 token-count row (penalties input). NOT recomputable
    #: from the Request: stop-stripped tokens stay counted.
    counts_row: Optional[np.ndarray] = None
    #: [2] uint32 RNG key data — the slot's key stream position
    key_data: Optional[np.ndarray] = None


@dataclass
class ParkedRequests:
    """Everything a preemption displaced, host-resident: what the model
    pool byte-counts alongside the slept weights and what
    ``resume_parked`` re-seats after the wake/swap-back."""

    #: mid-decode requests with live KV (ParkedRequest each)
    live: List[ParkedRequest] = field(default_factory=list)
    #: queued requests with no device state yet (engine Request objects;
    #: includes mid-prefill requests demoted back to the queue — prefill
    #: is a pure function of the prompt and consumes no key split until
    #: its final segment, so re-running it is bit-exact)
    waiting: List[Any] = field(default_factory=list)
    #: unique old-pool page ids in gather order (axis 1 of k/v_host)
    page_ids: List[int] = field(default_factory=list)
    #: gathered live pages [num_layers, len(page_ids), page_size, kvh, hd]
    k_host: Optional[np.ndarray] = None
    v_host: Optional[np.ndarray] = None
    kv_nbytes: int = 0
    #: pool-budget accounting: KV payload + scheduler-row metadata
    nbytes: int = 0
    #: service-owned: seq_id -> concurrent Future for live+waiting
    futures: Dict[int, Any] = field(default_factory=dict)
    #: service-owned: raw ``_pending`` submit tuples parked on swap
    pending: List[Any] = field(default_factory=list)
    #: the PURE d2h page-out window (gather_pages_d2h only — the engine
    #: quiesce and host bookkeeping around it excluded): what the
    #: kvsave.d2h bandwidth EWMA observes and priced sleep records score
    #: against, same discipline as sleep.d2h's pure transfer window
    pageout_s: float = 0.0

    @property
    def preempted(self) -> int:
        return len(self.live) + len(self.waiting) + len(self.pending)


def _pool_page_nbytes(k_pages: Any, v_pages: Any) -> int:
    """Bytes one page occupies across k+v and all layers, derived from
    the live pool arrays (shape [layers, num_pages, page_size, kvh, hd])."""
    n = max(1, int(k_pages.shape[1]))
    return (int(k_pages.nbytes) + int(v_pages.nbytes)) // n


def _chunks(n: int, per_chunk: int) -> List[Tuple[int, int]]:
    out = []
    i = 0
    while i < n:
        j = min(n, i + per_chunk)
        out.append((i, j))
        i = j
    return out


#: ONE jitted donated scatter for every resume (lazy: module import must
#: not touch a backend): jit's cache keys on function identity, so a
#: per-call lambda would recompile the scatter inside every resume
#: window — the compile-in-transfer-window cost warm_quant_ops exists to
#: avoid — and pollute the kvrestore.h2d bandwidth EWMA with compile time
_SCATTER = None


def _scatter_fn():
    global _SCATTER
    if _SCATTER is None:
        import jax

        _SCATTER = jax.jit(
            lambda pages, idx, vals: pages.at[:, idx].set(vals),
            donate_argnums=(0,),
        )
    return _SCATTER


def gather_pages_d2h(
    pool: Any,
    page_ids: Sequence[int],
    bucket_bytes: Optional[int] = None,
    span_name: str = "swap.kv_pageout",
) -> Tuple[np.ndarray, np.ndarray]:
    """Page the listed pool pages to host, chunk by chunk: gather a
    chunk's pages on device, move it D2H, free the device staging, then
    issue the next chunk — peak extra HBM is one chunk. Fires the
    ``kvsave.d2h`` fault point per chunk. Pure: the pool is read, never
    written, so a mid-transfer failure leaves the engine untouched and
    the caller falls back to the abort path."""
    import jax
    import jax.numpy as jnp

    ids = list(page_ids)
    per_page = _pool_page_nbytes(pool.k_pages, pool.v_pages)
    bucket = bucket_bytes or DEFAULT_KV_CHUNK_BYTES
    per_chunk = max(1, int(bucket) // max(1, per_page))
    layers, _, ps, kvh, hd = pool.k_pages.shape
    k_host = np.empty((layers, len(ids), ps, kvh, hd), pool.k_pages.dtype)
    v_host = np.empty_like(k_host)
    traced = tracing.enabled()
    parent = tracing.current_context() if traced else None
    for lo, hi in _chunks(len(ids), per_chunk):
        sp = None
        if traced:
            sp = tracing.begin(
                span_name, parent=parent, activate=False,
                pages=hi - lo, bytes=(hi - lo) * per_page,
            )
        try:
            faults.fire("kvsave.d2h")
            idx = jnp.asarray(ids[lo:hi], jnp.int32)
            k_sel = jnp.take(pool.k_pages, idx, axis=1)
            v_sel = jnp.take(pool.v_pages, idx, axis=1)
            kh, vh = jax.device_get((k_sel, v_sel))
            # materialized copies: device_get can return views aliasing
            # buffers on CPU-family backends (same rule as sleep staging)
            k_host[:, lo:hi] = np.asarray(kh)
            v_host[:, lo:hi] = np.asarray(vh)
            k_sel.delete()
            v_sel.delete()
        except BaseException as e:
            if sp is not None:
                sp.set(error=f"{type(e).__name__}: {e}")
                sp.end()
            raise
        if sp is not None:
            sp.end()
    return k_host, v_host


def scatter_pages_h2d(
    pool: Any,
    pairs: Sequence[Tuple[int, int]],
    k_host: np.ndarray,
    v_host: np.ndarray,
    bucket_bytes: Optional[int] = None,
    span_name: str = "wake.kv_pagein",
) -> int:
    """Page parked KV back into the (fresh) pool: ``pairs`` maps source
    index (axis 1 of k/v_host) -> destination page id. Chunked H2D with
    the ``kvrestore.h2d`` fault point per chunk; the pool arrays are
    updated in place via donated jit scatters (no whole-pool copy per
    chunk). Returns the wire bytes moved. A failure propagates with the
    pool left VALID (partially restored pages are only reachable once
    the caller seats page tables, which it never does after a failure)."""
    import jax
    import jax.numpy as jnp

    if not pairs:
        return 0
    per_page = _pool_page_nbytes(pool.k_pages, pool.v_pages)
    bucket = bucket_bytes or DEFAULT_KV_CHUNK_BYTES
    per_chunk = max(1, int(bucket) // max(1, per_page))
    scat = _scatter_fn()
    sharding = getattr(pool.k_pages, "sharding", None)
    moved = 0
    traced = tracing.enabled()
    parent = tracing.current_context() if traced else None
    for lo, hi in _chunks(len(pairs), per_chunk):
        chunk = pairs[lo:hi]
        sp = None
        if traced:
            sp = tracing.begin(
                span_name, parent=parent, activate=False,
                pages=len(chunk), bytes=len(chunk) * per_page,
            )
        try:
            faults.fire("kvrestore.h2d")
            src = [s for s, _ in chunk]
            dst = jnp.asarray([d for _, d in chunk], jnp.int32)
            kh = np.ascontiguousarray(k_host[:, src])
            vh = np.ascontiguousarray(v_host[:, src])
            if sharding is not None:
                # land the chunk pre-sharded like the pool it joins (the
                # kvh axis is 'tp'-sharded on meshes; NamedSharding is
                # shape-agnostic, so the pool's own sharding applies)
                kd, vd = jax.device_put((kh, vh), (sharding, sharding))
            else:
                kd, vd = jax.device_put((kh, vh))
            pool.k_pages = scat(pool.k_pages, dst, kd)
            pool.v_pages = scat(pool.v_pages, dst, vd)
            jax.block_until_ready((pool.k_pages, pool.v_pages))
            moved += kh.nbytes + vh.nbytes
        except BaseException as e:
            if sp is not None:
                sp.set(error=f"{type(e).__name__}: {e}")
                sp.end()
            raise
        if sp is not None:
            sp.end()
    return moved


# -- wire format: transactional parked-bundle handoff between instances
# (GET /v1/parked/{model} export, POST /v1/parked import; ROADMAP item 3a,
# docs/operations.md "Draining a node without dropping streams") ------------
#
# A bundle on the wire is a single JSON document: the KV page payload is
# chunked (whole pages, the same bucket discipline as the transfers above)
# with a sha256 content digest PER CHUNK — the importer verifies every
# digest before any device mutation, so a corrupted or truncated handoff is
# rejected with the destination untouched. Scheduler rows and the RNG key
# stream position ride per request, so the importer's ``resume_parked``
# continues the stream bit-exact on other silicon. The ``identity`` block
# (model name @ checkpoint + weight-digest fingerprint) pins which weights
# the bundle may seat onto; the ``fence`` block (added by the exporting
# service) makes the handoff single-use.

#: Request fields that serialize verbatim (JSON-able scalars/lists).
#: ``stop_seqs``/``logit_bias``/``out_top_logprobs`` need shape fixups and
#: are handled explicitly; device-derived state (pages, slot) never travels
#: — the importer re-derives it through resume_parked's old->new page map.
_REQ_WIRE_FIELDS = (
    "prompt", "max_new_tokens", "temperature", "top_p",
    "presence_penalty", "frequency_penalty", "want_top_logprobs",
    "want_prompt_logprobs", "seed", "ignore_eos", "out_tokens",
    "out_logprobs", "prompt_logprobs", "pos", "cached_tokens",
    "streamed", "stop_requested", "variant",
)


def pack_array(a: np.ndarray) -> Dict[str, Any]:
    """One small host array as a JSON-able {b64, dtype, shape} triple
    (scheduler counts rows, RNG key data)."""
    a = np.ascontiguousarray(a)
    return {
        "b64": base64.b64encode(a.tobytes()).decode("ascii"),
        "dtype": str(a.dtype),
        "shape": list(a.shape),
    }


def unpack_array(d: Dict[str, Any]) -> np.ndarray:
    return (
        np.frombuffer(base64.b64decode(d["b64"]), dtype=_np_dtype(d["dtype"]))
        .reshape(tuple(int(x) for x in d["shape"]))
        .copy()
    )


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16 and friends are registered by ml_dtypes (a jax
        # dependency), reachable by attribute even when the string
        # lookup is not
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def weight_fingerprint(digests: Dict[str, str]) -> str:
    """Order-independent sha256 fingerprint over a checkpoint's
    flat-key -> content-digest map: two engines hold the SAME weights
    iff their fingerprints match, which is what gates seating a
    migrated bundle (a bundle on mismatched weights would decode
    garbage from valid-looking KV)."""
    h = hashlib.sha256()
    for k in sorted(digests):
        h.update(f"{k}:{digests[k]}\n".encode())
    return h.hexdigest()


def encode_request(req: Any) -> Dict[str, Any]:
    """One engine Request as a JSON-able spec (host state only)."""
    spec = {k: getattr(req, k) for k in _REQ_WIRE_FIELDS}
    spec["seq_id"] = int(req.seq_id)
    spec["stop_seqs"] = [list(s) for s in req.stop_seqs]
    spec["logit_bias"] = {str(t): float(v) for t, v in req.logit_bias.items()}
    spec["out_top_logprobs"] = [
        [[int(t), float(v)] for t, v in alts] for alts in req.out_top_logprobs
    ]
    if getattr(req, "trace", None) is not None:
        # origin trace context: destination request.* spans parent on the
        # source's lifecycle root, so one trace_id covers both chips.
        # Optional field — WIRE_VERSION unchanged; old importers ignore it.
        ctx = req.trace.context()
        spec["trace"] = {"trace_id": ctx.trace_id, "span_id": ctx.span_id}
    return spec


def decode_request(spec: Dict[str, Any], request_cls: Any) -> Any:
    """Rebuild an engine Request from its wire spec. The seq_id is the
    EXPORTER'S — the importing service re-keys it with a fresh local id
    before seating (two engines' id spaces are unrelated)."""
    req = request_cls(
        seq_id=int(spec["seq_id"]),
        prompt=[int(t) for t in spec["prompt"]],
        max_new_tokens=int(spec["max_new_tokens"]),
        temperature=float(spec["temperature"]),
    )
    req.top_p = float(spec["top_p"])
    req.presence_penalty = float(spec["presence_penalty"])
    req.frequency_penalty = float(spec["frequency_penalty"])
    req.want_top_logprobs = bool(spec["want_top_logprobs"])
    req.want_prompt_logprobs = bool(spec["want_prompt_logprobs"])
    req.seed = None if spec["seed"] is None else int(spec["seed"])
    req.ignore_eos = bool(spec["ignore_eos"])
    req.out_tokens = [int(t) for t in spec["out_tokens"]]
    req.out_logprobs = [float(v) for v in spec["out_logprobs"]]
    req.prompt_logprobs = [
        None if v is None else float(v) for v in spec["prompt_logprobs"]
    ]
    req.pos = int(spec["pos"])
    req.cached_tokens = int(spec["cached_tokens"])
    req.streamed = int(spec["streamed"])
    req.stop_requested = bool(spec["stop_requested"])
    req.variant = int(spec["variant"])
    req.stop_seqs = tuple(tuple(int(t) for t in s) for s in spec["stop_seqs"])
    req.logit_bias = {int(t): float(v) for t, v in spec["logit_bias"].items()}
    req.out_top_logprobs = [
        [(int(t), float(v)) for t, v in alts]
        for alts in spec["out_top_logprobs"]
    ]
    tr = spec.get("trace")
    if isinstance(tr, dict) and tr.get("trace_id"):
        req.trace_parent = {
            "trace_id": str(tr["trace_id"]),
            "span_id": str(tr.get("span_id", "")),
        }
    return req


def encode_wire(
    bundle: ParkedRequests,
    identity: Dict[str, Any],
    chunk_bytes: Optional[int] = None,
) -> Dict[str, Any]:
    """Serialize a parked bundle for the handoff wire. ``identity`` is
    the exporting service's model-identity block (weight_fingerprint et
    al.); the caller adds the fence and the service-level request lists
    (pending submissions, seed-None RNG carry-over) it alone owns."""
    chunks: List[Dict[str, Any]] = []
    kv: Dict[str, Any] = {
        "page_ids": [int(p) for p in bundle.page_ids],
        "nbytes": int(bundle.kv_nbytes),
        "chunks": chunks,
    }
    if bundle.page_ids:
        k_host, v_host = bundle.k_host, bundle.v_host
        kv["dtype"] = str(k_host.dtype)
        kv["shape"] = list(k_host.shape)
        per_page = (int(k_host.nbytes) + int(v_host.nbytes)) // max(
            1, len(bundle.page_ids)
        )
        per_chunk = max(
            1,
            int(chunk_bytes or DEFAULT_KV_CHUNK_BYTES) // max(1, per_page),
        )
        for lo, hi in _chunks(len(bundle.page_ids), per_chunk):
            kb = np.ascontiguousarray(k_host[:, lo:hi]).tobytes()
            vb = np.ascontiguousarray(v_host[:, lo:hi]).tobytes()
            h = hashlib.sha256(kb)
            h.update(vb)
            chunks.append(
                {
                    "lo": lo,
                    "hi": hi,
                    "k": base64.b64encode(kb).decode("ascii"),
                    "v": base64.b64encode(vb).decode("ascii"),
                    "sha256": h.hexdigest(),
                }
            )
    live = []
    for pr in bundle.live:
        spec = encode_request(pr.req)
        spec["old_pages"] = [int(p) for p in pr.old_pages]
        spec["counts_row"] = pack_array(pr.counts_row)
        spec["key_data"] = pack_array(pr.key_data)
        live.append(spec)
    return {
        "version": WIRE_VERSION,
        "identity": dict(identity),
        "kv": kv,
        "requests": {
            "live": live,
            "waiting": [encode_request(r) for r in bundle.waiting],
            "pending": [],
        },
        "pageout_s": float(bundle.pageout_s),
        "nbytes": int(bundle.nbytes),
    }


def decode_wire(
    doc: Dict[str, Any], request_cls: Any
) -> Tuple[ParkedRequests, List[Dict[str, Any]]]:
    """Rebuild a parked bundle from a wire document, verifying EVERY KV
    chunk's content digest before returning — the caller touches no
    device state until this succeeds, so a bad handoff is rejected with
    the importer clean. Raises ValueError on any mismatch. Returns
    ``(bundle, pending_specs)``; pending submissions are service-level
    and the caller rebuilds their queue entries itself."""
    if int(doc.get("version", -1)) != WIRE_VERSION:
        raise ValueError(
            f"parked wire version {doc.get('version')!r} != {WIRE_VERSION}"
        )
    kv = doc["kv"]
    page_ids = [int(p) for p in kv["page_ids"]]
    k_host = v_host = None
    if page_ids:
        dtype = _np_dtype(kv["dtype"])
        shape = tuple(int(x) for x in kv["shape"])
        if shape[1] != len(page_ids):
            raise ValueError("KV shape does not match the page list")
        k_host = np.empty(shape, dtype)
        v_host = np.empty_like(k_host)
        covered = 0
        for ch in kv["chunks"]:
            lo, hi = int(ch["lo"]), int(ch["hi"])
            kb = base64.b64decode(ch["k"])
            vb = base64.b64decode(ch["v"])
            h = hashlib.sha256(kb)
            h.update(vb)
            if h.hexdigest() != ch["sha256"]:
                raise ValueError(
                    f"KV chunk [{lo}:{hi}] content digest mismatch"
                )
            sub = (shape[0], hi - lo) + shape[2:]
            k_host[:, lo:hi] = np.frombuffer(kb, dtype).reshape(sub)
            v_host[:, lo:hi] = np.frombuffer(vb, dtype).reshape(sub)
            covered += hi - lo
        if covered != len(page_ids):
            raise ValueError("KV chunks do not cover the page list")
    bundle = ParkedRequests(
        page_ids=page_ids,
        k_host=k_host,
        v_host=v_host,
        kv_nbytes=int(kv.get("nbytes", 0)),
        nbytes=int(doc.get("nbytes", 0)),
        pageout_s=float(doc.get("pageout_s", 0.0)),
    )
    reqs = doc["requests"]
    for spec in reqs["live"]:
        req = decode_request(spec, request_cls)
        bundle.live.append(
            ParkedRequest(
                req=req,
                old_pages=[int(p) for p in spec["old_pages"]],
                counts_row=unpack_array(spec["counts_row"]),
                key_data=unpack_array(spec["key_data"]),
            )
        )
    for spec in reqs["waiting"]:
        req = decode_request(spec, request_cls)
        if spec.get("rng_key_data") is not None:
            # seed-None sampled requests: the exporter pins the exact
            # initial key its own engine would have derived from
            # (engine seed, seq_id) — the importer's ids differ, and
            # without this the resumed stream would sample differently
            req.rng_key_data = unpack_array(spec["rng_key_data"])
        bundle.waiting.append(req)
    return bundle, list(reqs.get("pending", ()))
