"""Zero-drain actuation: live request state, paged out like weights.

Today an actuation and the requests it preempts are mutually exclusive:
a swap aborts every queued and in-flight request of the outgoing model.
The paged KV cache makes request state chunkable exactly the way weights
are — a request's KV lives in whole pages, its scheduler state in small
per-slot host rows — so the transactional sleep/swap discipline extends
to requests: **park** them (page the live KV pages to host, capture the
per-slot scheduler rows and RNG key state), store the bundle alongside
the slept weights in the model pool, and **resume** them bit-exact after
the wake/swap-back (page the KV back in, re-seat page tables and slots).

This module holds the data shapes and the two transfer primitives; the
park/resume *orchestration* lives on :class:`~.engine.InferenceEngine`
(it owns the scheduler state being detached/re-seated) and the service
wires it into the swap/sleep verbs behind ``--zero-drain``
(engine/server.py).

Transfer discipline matches engine/sleep.py: size-bounded chunks (whole
pages, never split), each chunk landed before the next is issued, with
named fault-injection points (``kvsave.d2h`` on page-out,
``kvrestore.h2d`` on page-in — utils/faults.py) so the failure paths are
deterministically drillable. A page-out failure leaves the engine
untouched (the caller falls back to the abort path); a page-in failure
is rolled back to a *clean* abort of the parked requests with the
existing ``state_loss`` cause — never a wedged slot or a corrupted page
table.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import faults, tracing

#: chunk bound fallback when the caller passes none: matches the swap
#: bucket default (engine/sleep.py DEFAULT_SWAP_BUCKET_BYTES)
DEFAULT_KV_CHUNK_BYTES = 256 << 20


class ParkedResumeFailed(RuntimeError):
    """A zero-drain resume failed mid page-in and was rolled back: no
    slot was seated, every allocated page was returned, and the engine
    is healthy with an empty (fresh) KV pool. The parked requests' KV is
    unrecoverable — the caller aborts them with cause ``state_loss``."""


@dataclass
class ParkedRequest:
    """One preempted mid-generation request: the pure-host Request
    object plus the device-derived state a bit-exact resume needs."""

    req: Any  #: engine.Request — prompt, emitted tokens, sampling knobs
    #: pool page ids (old pool) holding this request's live KV, page-table
    #: order — the first ``ceil(pos / page_size)`` of its allocation
    old_pages: List[int] = field(default_factory=list)
    #: [vocab] int32 token-count row (penalties input). NOT recomputable
    #: from the Request: stop-stripped tokens stay counted.
    counts_row: Optional[np.ndarray] = None
    #: [2] uint32 RNG key data — the slot's key stream position
    key_data: Optional[np.ndarray] = None


@dataclass
class ParkedRequests:
    """Everything a preemption displaced, host-resident: what the model
    pool byte-counts alongside the slept weights and what
    ``resume_parked`` re-seats after the wake/swap-back."""

    #: mid-decode requests with live KV (ParkedRequest each)
    live: List[ParkedRequest] = field(default_factory=list)
    #: queued requests with no device state yet (engine Request objects;
    #: includes mid-prefill requests demoted back to the queue — prefill
    #: is a pure function of the prompt and consumes no key split until
    #: its final segment, so re-running it is bit-exact)
    waiting: List[Any] = field(default_factory=list)
    #: unique old-pool page ids in gather order (axis 1 of k/v_host)
    page_ids: List[int] = field(default_factory=list)
    #: gathered live pages [num_layers, len(page_ids), page_size, kvh, hd]
    k_host: Optional[np.ndarray] = None
    v_host: Optional[np.ndarray] = None
    kv_nbytes: int = 0
    #: pool-budget accounting: KV payload + scheduler-row metadata
    nbytes: int = 0
    #: service-owned: seq_id -> concurrent Future for live+waiting
    futures: Dict[int, Any] = field(default_factory=dict)
    #: service-owned: raw ``_pending`` submit tuples parked on swap
    pending: List[Any] = field(default_factory=list)
    #: the PURE d2h page-out window (gather_pages_d2h only — the engine
    #: quiesce and host bookkeeping around it excluded): what the
    #: kvsave.d2h bandwidth EWMA observes and priced sleep records score
    #: against, same discipline as sleep.d2h's pure transfer window
    pageout_s: float = 0.0

    @property
    def preempted(self) -> int:
        return len(self.live) + len(self.waiting) + len(self.pending)


def _pool_page_nbytes(k_pages: Any, v_pages: Any) -> int:
    """Bytes one page occupies across k+v and all layers, derived from
    the live pool arrays (shape [layers, num_pages, page_size, kvh, hd])."""
    n = max(1, int(k_pages.shape[1]))
    return (int(k_pages.nbytes) + int(v_pages.nbytes)) // n


def _chunks(n: int, per_chunk: int) -> List[Tuple[int, int]]:
    out = []
    i = 0
    while i < n:
        j = min(n, i + per_chunk)
        out.append((i, j))
        i = j
    return out


#: ONE jitted donated scatter for every resume (lazy: module import must
#: not touch a backend): jit's cache keys on function identity, so a
#: per-call lambda would recompile the scatter inside every resume
#: window — the compile-in-transfer-window cost warm_quant_ops exists to
#: avoid — and pollute the kvrestore.h2d bandwidth EWMA with compile time
_SCATTER = None


def _scatter_fn():
    global _SCATTER
    if _SCATTER is None:
        import jax

        _SCATTER = jax.jit(
            lambda pages, idx, vals: pages.at[:, idx].set(vals),
            donate_argnums=(0,),
        )
    return _SCATTER


def gather_pages_d2h(
    pool: Any,
    page_ids: Sequence[int],
    bucket_bytes: Optional[int] = None,
    span_name: str = "swap.kv_pageout",
) -> Tuple[np.ndarray, np.ndarray]:
    """Page the listed pool pages to host, chunk by chunk: gather a
    chunk's pages on device, move it D2H, free the device staging, then
    issue the next chunk — peak extra HBM is one chunk. Fires the
    ``kvsave.d2h`` fault point per chunk. Pure: the pool is read, never
    written, so a mid-transfer failure leaves the engine untouched and
    the caller falls back to the abort path."""
    import jax
    import jax.numpy as jnp

    ids = list(page_ids)
    per_page = _pool_page_nbytes(pool.k_pages, pool.v_pages)
    bucket = bucket_bytes or DEFAULT_KV_CHUNK_BYTES
    per_chunk = max(1, int(bucket) // max(1, per_page))
    layers, _, ps, kvh, hd = pool.k_pages.shape
    k_host = np.empty((layers, len(ids), ps, kvh, hd), pool.k_pages.dtype)
    v_host = np.empty_like(k_host)
    traced = tracing.enabled()
    parent = tracing.current_context() if traced else None
    for lo, hi in _chunks(len(ids), per_chunk):
        sp = None
        if traced:
            sp = tracing.begin(
                span_name, parent=parent, activate=False,
                pages=hi - lo, bytes=(hi - lo) * per_page,
            )
        try:
            faults.fire("kvsave.d2h")
            idx = jnp.asarray(ids[lo:hi], jnp.int32)
            k_sel = jnp.take(pool.k_pages, idx, axis=1)
            v_sel = jnp.take(pool.v_pages, idx, axis=1)
            kh, vh = jax.device_get((k_sel, v_sel))
            # materialized copies: device_get can return views aliasing
            # buffers on CPU-family backends (same rule as sleep staging)
            k_host[:, lo:hi] = np.asarray(kh)
            v_host[:, lo:hi] = np.asarray(vh)
            k_sel.delete()
            v_sel.delete()
        except BaseException as e:
            if sp is not None:
                sp.set(error=f"{type(e).__name__}: {e}")
                sp.end()
            raise
        if sp is not None:
            sp.end()
    return k_host, v_host


def scatter_pages_h2d(
    pool: Any,
    pairs: Sequence[Tuple[int, int]],
    k_host: np.ndarray,
    v_host: np.ndarray,
    bucket_bytes: Optional[int] = None,
    span_name: str = "wake.kv_pagein",
) -> int:
    """Page parked KV back into the (fresh) pool: ``pairs`` maps source
    index (axis 1 of k/v_host) -> destination page id. Chunked H2D with
    the ``kvrestore.h2d`` fault point per chunk; the pool arrays are
    updated in place via donated jit scatters (no whole-pool copy per
    chunk). Returns the wire bytes moved. A failure propagates with the
    pool left VALID (partially restored pages are only reachable once
    the caller seats page tables, which it never does after a failure)."""
    import jax
    import jax.numpy as jnp

    if not pairs:
        return 0
    per_page = _pool_page_nbytes(pool.k_pages, pool.v_pages)
    bucket = bucket_bytes or DEFAULT_KV_CHUNK_BYTES
    per_chunk = max(1, int(bucket) // max(1, per_page))
    scat = _scatter_fn()
    sharding = getattr(pool.k_pages, "sharding", None)
    moved = 0
    traced = tracing.enabled()
    parent = tracing.current_context() if traced else None
    for lo, hi in _chunks(len(pairs), per_chunk):
        chunk = pairs[lo:hi]
        sp = None
        if traced:
            sp = tracing.begin(
                span_name, parent=parent, activate=False,
                pages=len(chunk), bytes=len(chunk) * per_page,
            )
        try:
            faults.fire("kvrestore.h2d")
            src = [s for s, _ in chunk]
            dst = jnp.asarray([d for _, d in chunk], jnp.int32)
            kh = np.ascontiguousarray(k_host[:, src])
            vh = np.ascontiguousarray(v_host[:, src])
            if sharding is not None:
                # land the chunk pre-sharded like the pool it joins (the
                # kvh axis is 'tp'-sharded on meshes; NamedSharding is
                # shape-agnostic, so the pool's own sharding applies)
                kd, vd = jax.device_put((kh, vh), (sharding, sharding))
            else:
                kd, vd = jax.device_put((kh, vh))
            pool.k_pages = scat(pool.k_pages, dst, kd)
            pool.v_pages = scat(pool.v_pages, dst, vd)
            jax.block_until_ready((pool.k_pages, pool.v_pages))
            moved += kh.nbytes + vh.nbytes
        except BaseException as e:
            if sp is not None:
                sp.set(error=f"{type(e).__name__}: {e}")
                sp.end()
            raise
        if sp is not None:
            sp.end()
    return moved
