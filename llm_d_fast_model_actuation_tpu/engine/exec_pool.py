"""Executable pool + AOT warmup: compile programs while weights move.

The only real-TPU run to date put `ttft_after_wake` at 6.59 s and blamed
first-touch JIT compilation of the prefill/suffix/decode programs: the
persistent XLA disk cache only amortizes *repeat* compiles and still pays
deserialization + dispatch on the critical path. This module moves ALL of
that off the first-request path, the same way the streaming loader moved
weight movement off it (docs/perf.md):

  * :class:`ExecutablePool` — a bounded LRU of AOT-compiled executables
    keyed by (engine-config hash, mesh shape, dtype/quant, program, shape
    bucket), sitting beside the host model pool in the engine service.
    Entries optionally *spill* as serialized executables into the
    launcher's persistent compile-cache directory, so a pool entry
    survives an instance restart (TPU only by default: the XLA CPU
    backend has produced numerically different executables when
    deserialized across clients — the same reason the persistent cache is
    TPU-only in bench.py; set ``FMA_EXEC_SPILL=1`` to force).

  * :class:`WarmupTask` — a background thread that AOT-compiles the
    incoming model's programs via ``jax.jit(...).lower(...).compile()``
    concurrently with its weight transfer. Lowering + compilation is pure
    host-CPU work over abstract avals (no params, no device buffers), so
    it overlaps cleanly with the H2D/D2H DMA of a swap, prefetch staging,
    or a cold checkpoint load. The engine service kicks a task before the
    transfer starts and installs the results into the new engine's AOT
    table (``InferenceEngine.install_executable``) once both finish.

Trace spans: one ``warmup.overlap`` root per task with a ``warmup.compile``
child per compiled program, wall-anchored like every other span — the
Perfetto timeline shows compile riding under the ``swap.d2h``/
``coldload.h2d`` transfer spans (docs/tracing.md).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import pickle
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import tracing
from ..utils.hashing import canonical_json, sha256_hex

logger = logging.getLogger(__name__)

#: default pool entry size when XLA's memory analysis reports nothing —
#: generated code for these programs is typically O(100 KiB..MiB)
DEFAULT_EXEC_NBYTES = 1 << 20

#: programs the warmup driver knows how to compile; "chunk"'s bucket is the
#: fused step count T, "mixed"'s is engine.mixed_bucket(buffer rows,
#: page-table slice width), the others' is the prefill token bucket
WARM_PROGRAMS = ("prefill", "suffix", "chunk", "mixed")


def default_spill_dir() -> str:
    """Where spilled executables live: the launcher exports
    ``FMA_EXEC_SPILL_DIR`` next to its persistent XLA compile cache
    (launcher/main.py preload), so children of one launcher share spilled
    entries across restarts; standalone engines derive the same location
    from ``JAX_COMPILATION_CACHE_DIR``."""
    explicit = os.environ.get("FMA_EXEC_SPILL_DIR", "")
    if explicit:
        return explicit
    cache = os.environ.get("JAX_COMPILATION_CACHE_DIR", "")
    return os.path.join(cache, "exec-pool") if cache else ""


def spill_supported() -> bool:
    """Serialized-executable reload is trusted on TPU; on other backends
    deserialization across clients has flipped numerics (see module
    docstring), so spill is opt-in via ``FMA_EXEC_SPILL=1``."""
    forced = os.environ.get("FMA_EXEC_SPILL", "")
    if forced == "1":
        return True
    if forced == "0":
        return False
    import jax

    return jax.default_backend() == "tpu"


def parse_warmup_buckets(spec: str) -> Tuple[int, ...]:
    """``--warmup-buckets`` parser: comma-separated positive prefill token
    buckets (rounded up to the engine's power-of-two buckets at plan
    time). Empty disables AOT warmup."""
    spec = (spec or "").strip()
    if not spec:
        return ()
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            v = int(part)
        except ValueError:
            raise ValueError(f"--warmup-buckets entry {part!r} is not an int")
        if v <= 0:
            raise ValueError(f"--warmup-buckets entries must be > 0, got {v}")
        out.append(v)
    return tuple(out)


# -- identity -----------------------------------------------------------------


def _normalize_cfg(cfg):
    """Thread the resolved attention impl into the model config exactly
    like InferenceEngine.__init__ does, so a signature computed from the
    service's pre-build config equals one computed from the live
    engine.cfg."""
    from .engine import resolve_attention_impl

    impl = resolve_attention_impl(cfg.attention_impl)
    m = cfg.model
    if m.attention_impl != impl:
        m = dataclasses.replace(m, attention_impl=impl)
        cfg = dataclasses.replace(cfg, model=m)
    return cfg


def exec_signature(cfg, mesh_shape: Optional[Tuple[int, ...]] = None) -> str:
    """Identity of a compiled-program family: everything that changes the
    lowered program — the full model config (dtype/quantization included),
    batch/page geometry, sampling top-k, eos wiring, attention impl, mesh
    shape, backend, device generation, and the jax version the executable
    was built by. Device *kind* (v4 vs v5e, not just "tpu") matters because
    the spill dir can live on storage shared across a heterogeneous fleet —
    an executable must never deserialize onto a different TPU generation."""
    import jax

    cfg = _normalize_cfg(cfg)
    try:
        device_kind = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 — no devices = signature still usable
        device_kind = ""
    body = {
        "model": dataclasses.asdict(cfg.model),
        "max_batch": cfg.max_batch,
        "page_size": cfg.page_size,
        "num_pages": cfg.num_pages,
        "seq_len": cfg.seq_len,
        "eos": cfg.eos_token_id,
        "extra_eos": list(cfg.extra_eos_ids),
        "logprobs_topk": cfg.logprobs_topk,
        "mesh": list(mesh_shape) if mesh_shape else None,
        "backend": jax.default_backend(),
        "device": device_kind,
        "jax": jax.__version__,
    }
    return sha256_hex(canonical_json(body))[:16]


def exec_key(signature: str, program: str, bucket: int) -> str:
    return f"{signature}/{program}@{int(bucket)}"


def mesh_shape(mesh) -> Optional[Tuple[int, ...]]:
    """`exec_signature`'s mesh identity of an engine's mesh (None =
    single device) — the ONE definition shared by the warmup/compile
    side (WarmupTask) and the install/reinstall check (engine/server.py):
    two copies drifting apart would fail the post-build signature check
    for every swap and silently cost mesh engines their AOT warmup."""
    return (
        tuple(int(x) for x in mesh.devices.shape) if mesh is not None
        else None
    )


def warmup_plan(cfg, buckets) -> List[Tuple[str, int]]:
    """(program, bucket) pairs a warmup covers.

    Bucketed serving: the prefill AND suffix-prefill programs at each
    requested shape bucket (rounded up to the engine's power-of-two
    buckets), plus the decode chunk at T=decode_chunk — and T=1 where
    the drain-tail policy dispatches single steps.

    Packed serving (cfg.packed_serving): the per-bucket prefill/suffix
    programs are OFF the serving path, so the plan shrinks to the one or
    two [token_budget] shapes of the mixed program plus the decode
    chunks — log2(max_seq) prefill buckets collapse into ~2 shapes,
    which is what makes warm swaps of a packed engine faster."""
    import jax

    from .engine import mixed_bucket, packed_budget_shapes, prefill_bucket

    def _bucket(n: int) -> int:
        # the live dispatch's rounding, by construction: one shared
        # definition (engine.prefill_bucket) or warmed executables would
        # pool at buckets the engine never looks up
        return prefill_bucket(n, cfg.seq_len)

    plan: List[Tuple[str, int]] = []
    if not buckets:
        return plan
    if getattr(cfg, "packed_serving", False):
        # full page-table width per buffer shape: always correct for any
        # step; live dispatch additionally jits narrower KV widths on
        # first touch as sequences shorter than max_seq dominate
        for shape in packed_budget_shapes(cfg):
            plan.append(("mixed", mixed_bucket(shape, cfg.pages_per_seq)))
    else:
        for b in sorted({_bucket(int(x)) for x in buckets}):
            plan.append(("prefill", b))
            plan.append(("suffix", b))
    plan.append(("chunk", cfg.decode_chunk))
    dt = cfg.drain_tail
    if dt == "auto":
        dt = "chunk" if jax.default_backend() == "tpu" else "single"
    if dt == "single":
        plan.append(("chunk", 1))
    return plan


# -- abstract avals -----------------------------------------------------------


def _abstract_state(cfg, mesh=None):
    """Param-tree and KV-pool avals for `cfg`, with the shardings the
    engine actually uses — single-device committed when `mesh` is None,
    else the NamedShardings of the live build (params via the registry's
    logical-axis rules = exactly what ``shard_pytree`` device_puts; the
    KV pool sharded over kv_heads = exactly ``PagePool.create``). Shapes
    come from the registry's init (the same source of truth as the HF
    loader), so no weights are touched."""
    import jax

    from ..models.registry import init_params_for

    m = cfg.model
    params = jax.eval_shape(
        lambda k: init_params_for(k, m), jax.random.key(0)
    )
    if mesh is None:
        from jax.sharding import SingleDeviceSharding

        sharding = SingleDeviceSharding(jax.devices()[0])
        params = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(
                s.shape, s.dtype, sharding=sharding
            ),
            params,
        )
        kv_sharding = sharding
    else:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..models.registry import logical_axes_for
        from ..parallel.mesh import named_sharding

        def put(s, axes):
            sh = (
                NamedSharding(mesh, P()) if axes is None
                else named_sharding(mesh, axes)
            )
            return jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh)

        params = jax.tree.map(
            put, params, logical_axes_for(m),
            is_leaf=lambda x: x is None,
        )
        kv_sharding = NamedSharding(mesh, P(None, None, None, "tp", None))
    kv = jax.ShapeDtypeStruct(
        (m.num_layers, cfg.num_pages, cfg.page_size, m.num_kv_heads,
         m.head_dim),
        m.dtype,
        sharding=kv_sharding,
    )
    return params, (kv, kv)


def abstract_args(cfg, program: str, bucket: int, mesh=None) -> list:
    """The abstract call signature of one engine program, matching the
    live engine's dispatch exactly: params/cache are committed device
    arrays (sharded avals — NamedSharding under a mesh); scheduler
    arrays carry the placement of ``_upload_sched`` — plain
    single-device on one device, explicitly REPLICATED on a mesh
    (engine._sched_sharding: an AOT executable's input spec must match
    the live arrays or every dispatch TypeErrors back to jit);
    per-request host mirrors (tokens, temps, counts rows, keys) arrive
    as numpy and stay placement-free."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import SingleDeviceSharding

    if mesh is None:
        sched_sharding = SingleDeviceSharding(jax.devices()[0])
    else:
        from jax.sharding import NamedSharding, PartitionSpec

        sched_sharding = NamedSharding(mesh, PartitionSpec())
    m = cfg.model
    V = m.vocab_size
    b, p = cfg.max_batch, cfg.pages_per_seq
    params, cache = _abstract_state(cfg, mesh)
    A = jax.ShapeDtypeStruct
    f32, i32, u32 = jnp.float32, jnp.int32, jnp.uint32
    if program in ("prefill", "prefill_plp"):
        return [
            params, A((1, bucket), i32), A((1,), i32), cache, A((1, p), i32),
            A((1,), f32), A((1,), f32), A((1, V), i32), A((1,), f32),
            A((1,), f32), A((2,), u32), A((1, V), f32),
        ]
    if program in ("suffix", "suffix_plp"):
        return [
            params, A((1, bucket), i32), A((1, bucket), i32), A((1,), i32),
            A((1,), i32), cache, A((1, p), i32), A((1,), f32), A((1,), f32),
            A((1, V), i32), A((1,), f32), A((1,), f32), A((2,), u32),
            A((1, V), f32),
        ]

    def S(shape, dt):
        return A(shape, dt, sharding=sched_sharding)

    if program == "chunk":
        return [
            params, S((b,), i32), S((b,), i32), S((b,), i32), cache,
            S((b, p), i32), S((b,), f32), S((b,), f32), S((b, V), i32),
            S((b,), f32), S((b,), f32), S((b, 2), u32), S((b,), i32),
            S((b, V), f32),
        ]
    if program == "mixed":
        # bucket = engine.mixed_bucket(buffer rows, page-table width);
        # per-row metadata and the small slot-indexed sampling mirrors
        # arrive as host numpy (placement-free), like the live packed
        # dispatch; the page table and the [b, vocab] counts/bias are
        # DEVICE-RESIDENT scheduler state (the table at FULL width — the
        # program slices to the bucket's kvp internally)
        T = bucket >> 16
        return [
            params, A((T,), i32), A((T,), i32), A((T,), i32), A((T,), i32),
            A((b,), i32), A((b,), i32), A((b,), i32), cache,
            S((b, p), i32), A((b,), f32), A((b,), f32), S((b, V), i32),
            A((b,), f32), A((b,), f32), A((b, 2), u32), S((b, V), f32),
        ]
    raise ValueError(f"unknown warmup program {program!r}")


def compile_program(cfg, program: str, bucket: int, programs=None, mesh=None):
    """AOT-compile one engine program for `cfg` at `bucket`:
    ``jit(fn).lower(*avals).compile()`` — host-CPU work only. Returns the
    ``jax.stages.Compiled`` executable. `mesh` switches the param/cache
    avals to the live build's NamedShardings (sharded engines)."""
    from .engine import ProgramSet

    cfg = _normalize_cfg(cfg)
    ps = programs or _program_set(cfg, mesh)
    if program == "chunk":
        fn = ps.chunk(int(bucket))
    elif program == "mixed":
        fn = ps.mixed(int(bucket) & 0xFFFF)
    else:
        fn = {
            "prefill": ps.prefill,
            "prefill_plp": ps.prefill_plp,
            "suffix": ps.suffix,
            "suffix_plp": ps.suffix_plp,
        }[program]
    return fn.lower(*abstract_args(cfg, program, bucket, mesh=mesh)).compile()


def _program_set(cfg, mesh=None):
    """A ProgramSet matching the live engine's for (cfg, mesh): the
    mixed program's attention impl follows the device-kind x mesh x
    impl-flag routing matrix (ops/attention.py:resolve_ragged_impl —
    pallas stays pallas on meshes via the kernel's shard_map port,
    interpret-incapable CPU builds fall back to the XLA twin), exactly
    like InferenceEngine.__init__ — a warmup-compiled executable must
    trace the identical program."""
    from ..ops.attention import resolve_ragged_impl
    from .engine import ProgramSet

    cfg = _normalize_cfg(cfg)
    return ProgramSet(
        cfg.model, cfg.logprobs_topk, cfg.eos_token_id,
        mixed_impl=resolve_ragged_impl(cfg.model.attention_impl, mesh),
        mesh=mesh,
    )


def executable_nbytes(compiled, default: int = DEFAULT_EXEC_NBYTES) -> int:
    """Host footprint estimate for pool accounting: XLA's generated-code
    size when the backend reports one (CPU reports 0), else a nominal
    default — the budget bounds entry COUNT honestly either way."""
    try:
        ma = compiled.memory_analysis()
        nb = int(getattr(ma, "generated_code_size_in_bytes", 0) or 0)
        return nb if nb > 0 else default
    except Exception:  # noqa: BLE001 — backend-optional API
        return default


# -- the pool -----------------------------------------------------------------


@dataclasses.dataclass
class ExecEntry:
    key: str
    compiled: Any
    nbytes: int
    compile_s: float = 0.0
    stored_at: float = dataclasses.field(default_factory=time.monotonic)


class ExecutablePool:
    """Bounded LRU of AOT-compiled executables (see module docstring).

    ``budget_bytes <= 0`` disables pooling (every ``put`` is dropped, every
    ``get`` is a miss) — warmup still hands executables straight to the
    engine being built, the pool only adds reuse across builds.

    ``on_event(kind)`` (kind in hit|miss|eviction) lets the owning service
    mirror pool traffic into Prometheus counters without this module
    importing prometheus. Thread-safe: warmup threads put while /metrics
    reads."""

    def __init__(
        self,
        budget_bytes: int = 0,
        spill_dir: str = "",
        on_event: Optional[Callable[[str], None]] = None,
    ) -> None:
        self.budget_bytes = int(budget_bytes)
        self.spill_dir = spill_dir or ""
        self._mu = threading.Lock()
        self._entries: "OrderedDict[str, ExecEntry]" = OrderedDict()
        self._on_event = on_event or (lambda kind: None)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.spill_hits = 0
        self.spill_errors = 0
        # running compile-cost figures (survive evictions): what the cost
        # oracle uses as the per-program compile estimate for a swap whose
        # warmup cannot hide everything (utils/costs.py; GET /v1/costs)
        self.compiles_total = 0
        self.compile_s_total = 0.0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: str) -> bool:
        return key in self._entries

    @property
    def bytes_used(self) -> int:
        with self._mu:
            return sum(e.nbytes for e in self._entries.values())

    def keys(self) -> List[str]:
        with self._mu:
            return list(self._entries)

    # -- get / put -----------------------------------------------------------

    def get(self, key: str) -> Optional[Any]:
        """Executable for `key` (LRU-touched), trying a spill reload on an
        in-memory miss; None = genuine miss (the caller compiles)."""
        with self._mu:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                self._on_event("hit")
                return entry.compiled
        # a disabled pool (budget <= 0) must not serve spilled blobs from
        # prior runs either — every get is a genuine miss
        compiled, nbytes = (
            self._load_spilled(key) if self.budget_bytes > 0 else (None, 0)
        )
        if compiled is not None:
            with self._mu:
                self.hits += 1
                self.spill_hits += 1
                self._on_event("hit")
            # re-registers as MRU (re-spilling skipped: the file exists).
            # A blob bigger than the budget — it shrank across a restart —
            # is served this once but not re-registered: a bounce per get
            # would grow the eviction counter without any budget churn.
            if nbytes <= self.budget_bytes:
                self.put(key, compiled, nbytes, spill=False)
            return compiled
        with self._mu:
            self.misses += 1
            self._on_event("miss")
        return None

    def put(
        self,
        key: str,
        compiled: Any,
        nbytes: Optional[int] = None,
        compile_s: float = 0.0,
        spill: bool = True,
    ) -> List[ExecEntry]:
        """Register an executable as MRU and evict LRU entries until the
        byte budget holds; write-through spill (when supported) so the
        entry survives an instance restart. Returns the evicted entries."""
        nb = int(nbytes if nbytes is not None else executable_nbytes(compiled))
        entry = ExecEntry(key=key, compiled=compiled, nbytes=nb,
                          compile_s=compile_s)
        if compile_s > 0:
            # a genuinely-compiled entry (pool/spill hits pass 0): feed
            # the running mean the cost oracle estimates compiles from
            with self._mu:
                self.compiles_total += 1
                self.compile_s_total += float(compile_s)
        if self.budget_bytes <= 0:
            # pooling disabled: drop outright — no write-through spill (a
            # spilled blob would come back as a disk hit on the next get,
            # contradicting the "0 disables pooling" contract) and no
            # eviction count (that metric means budget pressure / device
            # release, not a disabled pool)
            return [entry]
        if nb > self.budget_bytes:
            # an entry that can never fit bounces itself — and is NOT
            # spilled: a persisted blob would reload, re-bounce, and
            # re-count an eviction on every later get of the same key
            with self._mu:
                self._entries.pop(key, None)
                self.evictions += 1
                self._on_event("eviction")
            return [entry]
        if spill:
            self._spill(entry)
        evicted: List[ExecEntry] = []
        with self._mu:
            # a same-key re-put is a refresh, not an eviction: the old
            # entry is replaced silently (no counter, not returned) — the
            # eviction metric means budget pressure / device release only
            self._entries.pop(key, None)
            self._entries[key] = entry
            while (
                sum(e.nbytes for e in self._entries.values())
                > self.budget_bytes
            ):
                _, victim = self._entries.popitem(last=False)
                evicted.append(victim)
            self.evictions += len(evicted)
            for _ in evicted:
                self._on_event("eviction")
        return evicted

    def drop_live(self) -> int:
        """Drop every in-memory executable (device release: they belong to
        the client being destroyed). Spilled copies stay on disk — a later
        ``get`` re-validates by reloading them on backends where spill is
        trusted."""
        with self._mu:
            n = len(self._entries)
            self._entries.clear()
            self.evictions += n
            for _ in range(n):
                self._on_event("eviction")
            return n

    def describe(self) -> Dict[str, Any]:
        return {
            "entries": len(self._entries),
            "bytes_used": self.bytes_used,
            "budget_bytes": self.budget_bytes,
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "spill_hits": self.spill_hits,
            "spill_errors": self.spill_errors,
            "spill_dir": self.spill_dir if self._spill_enabled() else "",
            "compiles_total": self.compiles_total,
            "compile_s_total": round(self.compile_s_total, 6),
            "mean_compile_s": round(
                self.compile_s_total / self.compiles_total, 6
            )
            if self.compiles_total
            else 0.0,
        }

    # -- spill ----------------------------------------------------------------

    def _spill_enabled(self) -> bool:
        return bool(self.spill_dir) and spill_supported()

    def _spill_path(self, key: str) -> str:
        return os.path.join(self.spill_dir, sha256_hex(key) + ".exec")

    def _spill(self, entry: ExecEntry) -> bool:
        if not self._spill_enabled():
            return False
        try:
            from jax.experimental import serialize_executable

            payload, in_tree, out_tree = serialize_executable.serialize(
                entry.compiled
            )
            os.makedirs(self.spill_dir, exist_ok=True)
            path = self._spill_path(entry.key)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                pickle.dump(
                    {
                        "key": entry.key,
                        "nbytes": entry.nbytes,
                        "payload": payload,
                        "in_tree": in_tree,
                        "out_tree": out_tree,
                    },
                    f,
                )
            os.replace(tmp, path)  # atomic: readers never see a torn file
            return True
        except Exception:  # noqa: BLE001 — spill is best-effort
            self.spill_errors += 1
            logger.warning(
                "executable spill failed for %s", entry.key, exc_info=True
            )
            return False

    def _load_spilled(self, key: str) -> Tuple[Optional[Any], int]:
        if not self._spill_enabled():
            return None, 0
        path = self._spill_path(key)
        if not os.path.isfile(path):
            return None, 0
        try:
            from jax.experimental import serialize_executable

            with open(path, "rb") as f:
                blob = pickle.load(f)
            if blob.get("key") != key:  # hash collision paranoia
                return None, 0
            compiled = serialize_executable.deserialize_and_load(
                blob["payload"], blob["in_tree"], blob["out_tree"]
            )
            return compiled, int(blob.get("nbytes", DEFAULT_EXEC_NBYTES))
        except Exception:  # noqa: BLE001 — a stale/corrupt spill is a miss
            self.spill_errors += 1
            logger.warning(
                "spilled executable reload failed for %s", key,
                exc_info=True,
            )
            return None, 0


# -- the warmup driver --------------------------------------------------------


class WarmupTask:
    """Background AOT warmup for one incoming engine config.

    Kicked by the service *before* the swap/prefetch/cold-load transfer
    starts; compiles (or pool-fetches) every (program, bucket) in
    ``warmup_plan`` on a daemon thread, then the service joins it via
    ``install(engine)`` once the weights have landed. ``abort()`` stops it
    between compiles (swap cancellation).

    ``overlap_stats(window)`` reports how much of the compile work rode
    under a transfer window — ``hidden_frac`` is compile seconds hidden
    under transfer ÷ total compile seconds, the headline the swap bench
    emits as ``overlap_hidden_compile_frac``.
    """

    def __init__(
        self,
        cfg,
        buckets,
        pool: Optional[ExecutablePool] = None,
        mesh=None,
        trace_parent=None,
        on_program: Optional[Callable[[str, float], None]] = None,
        start: bool = True,
    ) -> None:
        self.cfg = _normalize_cfg(cfg)
        self.pool = pool
        #: the engine's mesh (None = single device): sharded engines
        #: compile against NamedSharding avals and key their pool
        #: entries by mesh shape — an executable lowered for tp=2 must
        #: never install into a tp=4 build
        self.mesh = mesh
        self.mesh_shape = mesh_shape(mesh)
        self.signature = exec_signature(self.cfg, self.mesh_shape)
        self.plan = warmup_plan(self.cfg, buckets)
        self.results: Dict[Tuple[str, int], Any] = {}
        self.stats: Dict[str, Any] = {
            "programs": len(self.plan),
            "compiled": 0,
            "pool_hits": 0,
            "compile_s": 0.0,
            "aborted": False,
            "errors": [],
            "skipped": "",
        }
        self.t_start: Optional[float] = None
        self.t_end: Optional[float] = None
        #: set by the service to the transfer-window start, so hidden-
        #: compile accounting starts at the swap edge, not thread spawn
        self.window_start: Optional[float] = None
        self._abort = threading.Event()
        #: set by abort(drop_results=True): an in-flight compile's result
        #: must ALSO be discarded (device release — it belongs to the
        #: PJRT client being destroyed), not just the remaining plan
        self._drop_results = False
        #: guards `results` — the compile thread inserts while install()
        #: snapshots (an unguarded dict iteration can raise mid-install)
        self._results_mu = threading.Lock()
        self._trace_parent = trace_parent
        self._on_program = on_program
        self._thread: Optional[threading.Thread] = None
        if not self.plan:
            self.stats["skipped"] = "no buckets"
            self.t_start = self.t_end = time.monotonic()
        elif start:
            self.start()

    def start(self) -> None:
        if self._thread is not None or self.stats["skipped"]:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="aot-warmup"
        )
        self._thread.start()

    def abort(self, drop_results: bool = False) -> None:
        """Stop compiling between programs (swap cancellation): already-
        compiled executables stay pooled — the work is not wasted, the
        next attempt pool-hits them. ``drop_results=True`` (device
        release) additionally discards an in-flight compile's result
        instead of pooling it: the executable would belong to the PJRT
        client being destroyed, and a later pool hit would install a
        dead-client executable."""
        if drop_results:
            self._drop_results = True
        self._abort.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        if self._thread is not None:
            self._thread.join(timeout)
            return not self._thread.is_alive()
        return True

    def install(self, engine, timeout: Optional[float] = None) -> int:
        """Join the task and hand every executable to the engine. The
        caller is responsible for signature-checking against the built
        engine (the service compares ``exec_signature(engine.cfg)``)."""
        if not self.wait(timeout):
            # pathological compile outlasting the timeout: stop between
            # programs and install what finished (the rest jit-compiles)
            self.abort()
            self.wait(5)
        with self._results_mu:
            snapshot = list(self.results.items())
        n = 0
        for (program, bucket), compiled in snapshot:
            engine.install_executable(program, bucket, compiled)
            n += 1
        return n

    def overlap_stats(
        self, window_t0: Optional[float] = None,
        window_t1: Optional[float] = None,
    ) -> Dict[str, Any]:
        t0 = self.window_start if self.window_start is not None else self.t_start
        w0 = window_t0 if window_t0 is not None else t0
        w1 = window_t1 if window_t1 is not None else time.monotonic()
        hidden = 0.0
        if self.t_start is not None and self.t_end is not None and w0 is not None:
            hidden = max(0.0, min(self.t_end, w1) - max(self.t_start, w0))
        compile_s = self.stats["compile_s"]
        frac = min(1.0, hidden / compile_s) if compile_s > 0 else 0.0
        return {
            "programs": self.stats["programs"],
            "compiled": self.stats["compiled"],
            "pool_hits": self.stats["pool_hits"],
            "compile_s": round(compile_s, 6),
            "hidden_s": round(min(hidden, compile_s), 6),
            "hidden_frac": round(frac, 6),
            "aborted": self.stats["aborted"],
            "errors": list(self.stats["errors"]),
            "skipped": self.stats["skipped"],
            "signature": self.signature,
        }

    # -- thread body ----------------------------------------------------------

    def _run(self) -> None:
        self.t_start = time.monotonic()
        root = tracing.begin(
            "warmup.overlap",
            parent=self._trace_parent,
            activate=False,
            signature=self.signature,
            programs=len(self.plan),
        )
        traced = root is not tracing.NOOP_SPAN
        root_ctx = root.context() if traced else None
        ps = None
        # fma_engine_warmup_seconds{program} is a gauge: report the
        # CUMULATIVE compile seconds per program, not the last bucket's —
        # with several --warmup-buckets a per-bucket .set() would
        # undercount prefill/suffix by every bucket but the final one
        per_program: Dict[str, float] = {}
        try:
            for program, bucket in self.plan:
                if self._abort.is_set():
                    self.stats["aborted"] = True
                    break
                key = exec_key(self.signature, program, bucket)
                compiled = self.pool.get(key) if self.pool is not None else None
                if compiled is not None:
                    with self._results_mu:
                        self.results[(program, bucket)] = compiled
                    self.stats["pool_hits"] += 1
                    continue
                sp = None
                if traced:
                    sp = tracing.begin(
                        "warmup.compile", parent=root_ctx, activate=False,
                        program=program, bucket=bucket,
                    )
                t0 = time.monotonic()
                try:
                    if ps is None:
                        ps = _program_set(self.cfg, self.mesh)
                    compiled = compile_program(
                        self.cfg, program, bucket, programs=ps,
                        mesh=self.mesh,
                    )
                except Exception as e:  # noqa: BLE001 — warmup never fails a swap
                    self.stats["errors"].append(
                        f"{program}@{bucket}: {type(e).__name__}: {e}"
                    )
                    if sp is not None:
                        sp.set(error=f"{type(e).__name__}: {e}")
                        sp.end()
                    logger.warning(
                        "AOT warmup compile failed for %s@%s", program,
                        bucket, exc_info=True,
                    )
                    continue
                secs = time.monotonic() - t0
                if sp is not None:
                    sp.set(seconds=round(secs, 6))
                    sp.end()
                self.stats["compile_s"] += secs
                if self._abort.is_set() and self._drop_results:
                    # aborted by a device release while this compile was
                    # in flight: the executable is owned by the client
                    # being torn down — pooling it would hand a later
                    # build a dead-client executable
                    self.stats["aborted"] = True
                    break
                self.stats["compiled"] += 1
                with self._results_mu:
                    self.results[(program, bucket)] = compiled
                if self.pool is not None:
                    self.pool.put(
                        key, compiled, executable_nbytes(compiled),
                        compile_s=secs,
                    )
                if self._on_program is not None:
                    per_program[program] = per_program.get(program, 0.0) + secs
                    self._on_program(program, per_program[program])
        finally:
            self.t_end = time.monotonic()
            root.set(
                compiled=self.stats["compiled"],
                pool_hits=self.stats["pool_hits"],
                compile_s=round(self.stats["compile_s"], 6),
                aborted=self.stats["aborted"],
            )
            root.end()
