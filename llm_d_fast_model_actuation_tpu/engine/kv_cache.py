"""Paged KV cache: device page pools + host-side page allocator.

Pool layout (per k and v): ``[num_layers, num_pages, page_size, kv_heads,
head_dim]`` — one array for all layers so the layer axis can be scanned and
the whole pool moved HBM<->host in one transfer on sleep/wake. kv_heads is
sharded over `tp`; everything else replicated (pages are a node-local pool,
like vLLM's block allocator, not a distributed object).

Page size defaults to 16 tokens: with head_dim 128 a (16, kvh_shard*128)
page tile keeps the last dim at the TPU 128-lane boundary.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass
class PagePool:
    k_pages: jnp.ndarray
    v_pages: jnp.ndarray

    @staticmethod
    def pool_shape(
        num_layers: int,
        num_pages: int,
        page_size: int,
        num_kv_heads: int,
        head_dim: int,
    ) -> Tuple[int, int, int, int, int]:
        """The per-direction (k or v) pool array shape — the ONE
        definition shared by :meth:`create` and :meth:`estimate_nbytes`
        (the cost oracle sizes a not-yet-built pool from it)."""
        return (num_layers, num_pages, page_size, num_kv_heads, head_dim)

    @classmethod
    def estimate_nbytes(
        cls,
        num_layers: int,
        num_pages: int,
        page_size: int,
        num_kv_heads: int,
        head_dim: int,
        dtype: Any = jnp.bfloat16,
    ) -> int:
        """Device bytes a :meth:`create` with these arguments allocates
        (k + v), without allocating — what the actuation cost oracle
        counts into cold-tier predictions (engine/server.py
        _kv_pool_nbytes), kept here so a pool-layout change can never
        silently drift the prediction from the build's bytes_in."""
        import numpy as np

        shape = cls.pool_shape(
            num_layers, num_pages, page_size, num_kv_heads, head_dim
        )
        elems = 1
        for d in shape:
            elems *= int(d)
        return 2 * elems * int(np.dtype(dtype).itemsize)

    @classmethod
    def page_nbytes(
        cls,
        num_layers: int,
        page_size: int,
        num_kv_heads: int,
        head_dim: int,
        dtype: Any = jnp.bfloat16,
    ) -> int:
        """Device bytes ONE page occupies across all layers, k and v —
        what the zero-drain park (engine/parked.py) and its pre-transfer
        pricing multiply by the live page count, kept next to
        :meth:`estimate_nbytes` so both derive from the one pool layout."""
        return cls.estimate_nbytes(
            num_layers, 1, page_size, num_kv_heads, head_dim, dtype=dtype
        )

    @classmethod
    def create(
        cls,
        num_layers: int,
        num_pages: int,
        page_size: int,
        num_kv_heads: int,
        head_dim: int,
        dtype: Any = jnp.bfloat16,
        mesh: Optional[Mesh] = None,
    ) -> "PagePool":
        shape = cls.pool_shape(
            num_layers, num_pages, page_size, num_kv_heads, head_dim
        )
        if mesh is not None:
            sharding = NamedSharding(mesh, P(None, None, None, "tp", None))
            zeros = jax.jit(
                lambda: jnp.zeros(shape, dtype), out_shardings=sharding
            )
        else:
            zeros = lambda: jnp.zeros(shape, dtype)  # noqa: E731
        return cls(k_pages=zeros(), v_pages=zeros())

    @property
    def num_pages(self) -> int:
        return self.k_pages.shape[1]

    @property
    def page_size(self) -> int:
        return self.k_pages.shape[2]

    def nbytes(self) -> int:
        return self.k_pages.nbytes + self.v_pages.nbytes

    def as_tuple(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        return self.k_pages, self.v_pages

    def replace(self, kv: Tuple[jnp.ndarray, jnp.ndarray]) -> None:
        self.k_pages, self.v_pages = kv


class OutOfPages(Exception):
    """Page pool exhausted — the scheduler must preempt or queue."""


@dataclass
class PageAllocator:
    """Host-side free-list allocator over the pool's page indices.

    Page 0 is reserved as the null page (page tables are initialized to it),
    so sequences never alias a live page before assignment.
    """

    num_pages: int
    _free: List[int] = field(default_factory=list)
    #: monotonic mutation counter: bumps whenever the free list changes, so
    #: blocked-admission memos can key on "did anything move" exactly
    version: int = 0

    def __post_init__(self) -> None:
        if not self._free:
            self._free = list(range(self.num_pages - 1, 0, -1))

    @property
    def available(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> List[int]:
        if n > len(self._free):
            raise OutOfPages(f"need {n} pages, have {len(self._free)}")
        out = [self._free.pop() for _ in range(n)]
        if out:
            self.version += 1
        return out

    def free(self, pages: List[int]) -> None:
        returned = False
        for p in pages:
            if p == 0:
                continue
            self._free.append(p)
            returned = True
        if returned:
            self.version += 1

    @staticmethod
    def pages_needed(num_tokens: int, page_size: int) -> int:
        return -(-num_tokens // page_size)
