"""Multi-host serving data plane: leader/follower lockstep stepping.

A multi-host engine is ONE SPMD job: every gang process must execute the
same compiled programs in the same order with the same host-side inputs,
or the collectives inside them deadlock. The control plane
(controller/gang.py) forms the gang and `jax.distributed.initialize`
joins it; this module keeps the gang in lockstep while SERVING:

  * process 0 (the **leader**) runs the normal engine loop and the HTTP
    API. Before every compiled call it broadcasts a fixed-shape control
    frame — call kind, static args (prefill bucket / chunk length), and
    the host scheduler mirrors — via
    `jax.experimental.multihost_utils.broadcast_one_to_all` (itself a
    collective, so followers block until the leader has work);
  * processes 1..N-1 (**followers**) run `follower_loop`: receive a
    frame, replay the identical compiled call on their local shards, and
    keep their device state (KV pool, scheduler arrays, RNG key) in
    lockstep. Followers never sync tokens to host — the leader alone
    talks to clients.

Determinism argument: both sides start from the same seed (the gang's
ISC options are identical), every compiled call is the same program with
the same inputs, and scheduler edges (admission, retirement) exist only
on the leader — followers import their effects through the broadcast
mirrors. vLLM's multi-host TPU serving solves this with an RPC executor
broadcasting scheduler output per step; the lockstep frame is the
XLA-native equivalent (one small collective per compiled dispatch).

The frame is FIXED SHAPE for a given engine config, so the broadcast
compiles exactly once.
"""

from __future__ import annotations

import hashlib
import hmac
import logging
import os
import socket
import socketserver
import threading
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

logger = logging.getLogger(__name__)

#: shared-secret env for heartbeat authentication: every gang member gets
#: the same value from the coordinator's ISC env. Unset falls back to a
#: fixed default — the token then only proves "same coordinator address",
#: which still stops a stray prober on a hostNetwork node from keeping a
#: half-dead gang looking alive.
GANG_HB_SECRET_ENV = "FMA_GANG_HB_SECRET"


def gang_heartbeat_token(coordinator_address: str) -> str:
    """Per-gang heartbeat token: HMAC of the coordinator address under the
    shared secret. Binds a ping to THIS gang — two gangs whose heartbeat
    ports collide across restarts (the port is derived, not reserved)
    can no longer accept each other's pings, and an unauthenticated
    writer can't refresh a member's liveness."""
    secret = os.environ.get(GANG_HB_SECRET_ENV, "") or "fma-gang"
    return hmac.new(
        secret.encode(), coordinator_address.encode(), hashlib.sha256
    ).hexdigest()[:16]

#: Heartbeat port = coordinator port + this offset. The gang coordinator
#: draws per-gang coordinator ports from [base, base+4096) (controller/
#: gang.py), so base+4096.. is collision-free against other gangs'
#: coordinators on the same hostNetwork node.
HEARTBEAT_PORT_OFFSET = 4096

#: Exit code for "a gang peer died while the data plane may be blocked in
#: a collective" — the launcher's sentinel sees the process exit and the
#: crash chain (STOPPED -> notifier -> controller deletes the requester ->
#: gang degrades -> re-forms) takes over, the same path a single-host
#: engine crash takes (launcher/instance.py).
EXIT_GANG_PEER_LOST = 13


class GangWatchdog:
    """Data-plane failure detector for a lockstep gang.

    The lockstep protocol is built on collectives, and a collective whose
    participant died never completes — a wedged gang serves nothing and
    looks alive. The reference's failure chain is process-level (vLLM
    crash -> launcher sentinel -> controller deletes the server pod); this
    gives the gang's data plane the same property: any member death
    converts, within `timeout` seconds, into every other member exiting
    non-zero, which the per-member launchers' sentinels all see.

    Star topology over the leader's host (every member already knows the
    coordinator address; no extra discovery):

      * the leader runs a tiny TCP responder on coordinator_port +
        HEARTBEAT_PORT_OFFSET and tracks when each follower last pinged;
        a follower silent for `timeout` seconds (or never arrived within
        `join_grace`) kills the leader;
      * followers ping every `interval` seconds; a leader unreachable for
        `timeout` seconds kills the follower.

    A follower death thus kills the leader directly, and the leader's
    death cascades to the remaining followers — whole-gang teardown from
    any single fault, without requiring full pairwise connectivity.

    Heartbeats ride their own threads + sockets, never the collective
    stream, so a gang blocked in a healthy long collective (big prefill)
    keeps answering and is NOT torn down: timeouts fire only when a
    process is actually gone (its responder/prober dies with it).
    """

    def __init__(
        self,
        process_id: int,
        num_processes: int,
        coordinator_address: str,
        interval: float = 2.0,
        timeout: float = 20.0,
        join_grace: float = 60.0,
        on_death: Optional[Callable[[str], None]] = None,
    ) -> None:
        host, _, port = coordinator_address.rpartition(":")
        self.process_id = process_id
        self.num_processes = num_processes
        self.leader_host = host
        self.hb_port = int(port) + HEARTBEAT_PORT_OFFSET
        #: per-gang auth token (see gang_heartbeat_token): carried in
        #: every ping, verified by the responder — an unauthenticated
        #: ping refreshes nothing and gets no "ok"
        self.token = gang_heartbeat_token(coordinator_address)
        # a timeout needs several missed pings' slack, or scheduler jitter
        # on a single late ping reads as a death: keep >= 4 intervals per
        # timeout window by shrinking the interval for small timeouts
        self.interval = min(interval, max(0.05, timeout / 4.0))
        self.timeout = timeout
        self.join_grace = max(join_grace, timeout)
        self._on_death = on_death or self._die
        self._stop = threading.Event()
        self._server: Optional[socketserver.ThreadingTCPServer] = None
        self._threads: list = []
        #: leader: follower pid -> monotonic last-heard
        self._last_seen: Dict[int, float] = {}

    @staticmethod
    def _die(reason: str) -> None:
        logger.critical(
            "gang watchdog: %s — exiting %d so the launcher sentinel "
            "tears this member down (the data plane may be wedged in a "
            "collective and cannot unwind in-process)",
            reason, EXIT_GANG_PEER_LOST,
        )
        # not sys.exit: the lockstep thread may be blocked inside a
        # collective that will never return; only the process can die
        os._exit(EXIT_GANG_PEER_LOST)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self.num_processes <= 1:
            return
        if self.process_id == 0:
            self._start_responder()
            t = threading.Thread(
                target=self._leader_monitor, daemon=True,
                name="gang-hb-monitor",
            )
        else:
            t = threading.Thread(
                target=self._follower_prober, daemon=True,
                name="gang-hb-prober",
            )
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        """Clean shutdown (leader broadcast SHUTDOWN was delivered): stop
        probing/monitoring so the orderly teardown isn't misread as a
        death."""
        self._stop.set()
        if self._server is not None:
            try:
                self._server.shutdown()
                self._server.server_close()
            except Exception:  # noqa: BLE001
                pass

    # -- leader side ---------------------------------------------------------

    def _start_responder(self) -> None:
        last_seen = self._last_seen
        token = self.token

        class Handler(socketserver.StreamRequestHandler):
            def handle(self) -> None:
                try:
                    line = self.rfile.readline(96).decode().split()
                    # "hb <pid> <token>": the token must verify or the
                    # ping neither refreshes liveness nor gets an "ok" —
                    # a stray/foreign prober can't keep a dead member
                    # looking alive (constant-time compare: the token is
                    # a shared-secret MAC, not a public cookie)
                    if (
                        len(line) == 3
                        and line[0] == "hb"
                        and hmac.compare_digest(line[2], token)
                    ):
                        last_seen[int(line[1])] = time.monotonic()
                        self.wfile.write(b"ok\n")
                except (ValueError, OSError):
                    pass

        class _HBServer(socketserver.ThreadingTCPServer):
            # confined to the watchdog's server; mutating the stdlib class
            # attribute would flip SO_REUSEADDR on for unrelated servers
            allow_reuse_address = True

        try:
            self._server = _HBServer(("0.0.0.0", self.hb_port), Handler)
        except OSError as e:
            # name the port-derivation scheme: "address already in use" on
            # a number nobody configured is otherwise undebuggable
            raise RuntimeError(
                f"gang heartbeat responder failed to bind "
                f"0.0.0.0:{self.hb_port} (= coordinator port "
                f"{self.hb_port - HEARTBEAT_PORT_OFFSET} + "
                f"HEARTBEAT_PORT_OFFSET {HEARTBEAT_PORT_OFFSET}; the "
                f"gang coordinator draws coordinator ports from a range "
                f"whose +{HEARTBEAT_PORT_OFFSET} offset must stay free "
                f"on this node): {e}"
            ) from e
        self._server.daemon_threads = True
        t = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="gang-hb-server",
        )
        t.start()
        self._threads.append(t)

    def _leader_monitor(self) -> None:
        started = time.monotonic()
        expected = set(range(1, self.num_processes))
        while not self._stop.wait(self.interval):
            now = time.monotonic()
            for pid in expected:
                seen = self._last_seen.get(pid)
                if seen is None:
                    # jax.distributed.initialize returned, so the member
                    # process existed; its first ping should land within
                    # an interval or two
                    if now - started > self.join_grace:
                        self._on_death(
                            f"follower {pid} never sent a heartbeat "
                            f"within {self.join_grace:.0f}s of gang start"
                        )
                        return
                elif now - seen > self.timeout:
                    self._on_death(
                        f"follower {pid} heartbeat silent for "
                        f"{now - seen:.1f}s (> {self.timeout:.0f}s)"
                    )
                    return

    # -- follower side -------------------------------------------------------

    def _ping(self) -> bool:
        try:
            with socket.create_connection(
                (self.leader_host, self.hb_port), timeout=self.interval + 1
            ) as s:
                s.sendall(f"hb {self.process_id} {self.token}\n".encode())
                s.settimeout(self.interval + 1)
                return s.recv(8).startswith(b"ok")
        except OSError:
            return False

    def _follower_prober(self) -> None:
        last_ok = time.monotonic()
        reached = False  # leader responder answered at least once
        while not self._stop.wait(self.interval):
            if self._ping():
                last_ok = time.monotonic()
                reached = True
                continue
            silent = time.monotonic() - last_ok
            # before first contact the leader may still be compiling /
            # binding its responder: allow the same grace the leader gives
            # followers, then the steady-state timeout applies
            allowed = self.timeout if reached else self.join_grace
            if silent > allowed:
                self._on_death(
                    f"leader heartbeat unreachable for {silent:.1f}s "
                    f"(> {allowed:.0f}s)"
                )
                return

KIND_IDLE = 0
KIND_PREFILL = 1
KIND_CHUNK = 2
KIND_SLEEP = 3
KIND_WAKE = 4
KIND_SHUTDOWN = 5
KIND_PREFILL_SUFFIX = 6  #: prefix-cache hit: replay the continue program


def _frame_template(cfg) -> Dict[str, np.ndarray]:
    b, p = cfg.max_batch, cfg.pages_per_seq
    return {
        "kind": np.zeros((), np.int32),
        #: prefill bucket | chunk T | sleep level
        "arg": np.zeros((), np.int32),
        #: prefill slot | sleep release flag
        "arg2": np.zeros((), np.int32),
        "seq_len": np.zeros((), np.int32),
        #: suffix prefill: absolute position of the first suffix token
        "start": np.zeros((), np.int32),
        "temp": np.zeros((), np.float32),
        "top_p": np.ones((), np.float32),
        "tokens": np.zeros((cfg.seq_len,), np.int32),
        #: chunk: rebuild device scheduler state from the mirrors below
        "reupload": np.zeros((), np.int32),
        #: suffix prefill: thread the returned RNG key (1) or discard it
        #: (0 — non-final chunked-prefill segments)
        "advance_key": np.ones((), np.int32),
        "want_plp": np.zeros((), np.int32),
        "lt": np.zeros((b,), np.int32),
        "pos": np.zeros((b,), np.int32),
        "budget": np.zeros((b,), np.int32),
        "temps": np.zeros((b,), np.float32),
        "topps": np.ones((b,), np.float32),
        # penalties are rejected for gangs (engine.add_request): these stay
        # zero, which makes the samplers count-independent, so the [b,vocab]
        # count arrays themselves never need to cross the frame
        "pres": np.zeros((b,), np.float32),
        "freqs": np.zeros((b,), np.float32),
        "page_table": np.zeros((b, p), np.int32),
        #: per-slot RNG key data (per-request seed streams)
        "skeys": np.zeros((b, 2), np.uint32),
        #: per-slot eos sensitivity (ignore_eos requests = 0)
        "eos_on": np.ones((b,), np.int32),
    }


def _broadcast(frame: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    from jax.experimental import multihost_utils

    out = multihost_utils.broadcast_one_to_all(frame)
    return {k: np.asarray(v) for k, v in out.items()}


class LockstepLeader:
    """Installed on the leader's engine as `engine.lockstep`; the engine
    calls these hooks immediately before its compiled dispatches."""

    def __init__(self, engine: Any) -> None:
        self.engine = engine
        self._template = _frame_template(engine.cfg)

    def _mirrors(self, f: Dict[str, np.ndarray]) -> None:
        e = self.engine
        f["lt"] = e._last_tokens.copy()
        f["pos"] = e._positions.copy()
        f["budget"] = e._budgets.copy()
        f["temps"] = e._temps.copy()
        f["topps"] = e._topps.copy()
        f["pres"] = e._pres.copy()
        f["freqs"] = e._freqs.copy()
        f["page_table"] = e._page_table.copy()
        f["skeys"] = e._slot_keys.copy()
        f["eos_on"] = e._eos_on.copy()

    def _send(self, **fields: Any) -> None:
        f = dict(self._template)
        self._mirrors(f)
        for k, v in fields.items():
            f[k] = np.asarray(v, f[k].dtype)
        _broadcast(f)

    # -- hooks ---------------------------------------------------------------

    def prefill(self, req: Any, bucket: int, want_plp: bool = False) -> None:
        tokens = np.zeros((self.engine.cfg.seq_len,), np.int32)
        tokens[: len(req.prompt)] = req.prompt
        self._send(
            kind=KIND_PREFILL,
            arg=bucket,
            arg2=req.slot,
            seq_len=len(req.prompt),
            temp=req.temperature,
            top_p=req.top_p,
            tokens=tokens,
            want_plp=int(want_plp),
        )

    def prefill_suffix(
        self,
        req: Any,
        bucket: int,
        start: int,
        seg_len: int = -1,
        advance_key: bool = True,
        want_plp: bool = False,
    ) -> None:
        if seg_len < 0:
            seg_len = len(req.prompt) - start
        seg = req.prompt[start : start + seg_len]
        tokens = np.zeros((self.engine.cfg.seq_len,), np.int32)
        tokens[: len(seg)] = seg
        self._send(
            kind=KIND_PREFILL_SUFFIX,
            arg=bucket,
            arg2=req.slot,
            seq_len=len(seg),
            start=start,
            temp=req.temperature,
            top_p=req.top_p,
            tokens=tokens,
            advance_key=int(advance_key),
            want_plp=int(want_plp),
        )

    def chunk(self, T: int, reupload: bool) -> None:
        self._send(kind=KIND_CHUNK, arg=T, reupload=int(reupload))

    def sleep(self, level: int, release: bool) -> None:
        self._send(kind=KIND_SLEEP, arg=level, arg2=int(release))

    def wake(self) -> None:
        self._send(kind=KIND_WAKE)

    def shutdown(self) -> None:
        self._send(kind=KIND_SHUTDOWN)


def follower_loop(engine: Any, sleeper: Optional[Any] = None) -> None:
    """Run a follower process until the leader broadcasts SHUTDOWN.

    `engine` must be constructed identically to the leader's (same config,
    same seed, same mesh plan) — the gang ships identical ISC options to
    every member, so this holds by construction.
    """
    template = _frame_template(engine.cfg)
    while True:
        f = _broadcast(template)
        kind = int(f["kind"])
        if kind == KIND_SHUTDOWN:
            logger.info("follower: leader shut down")
            return
        if kind == KIND_PREFILL:
            _replay_prefill(engine, f)
        elif kind == KIND_PREFILL_SUFFIX:
            _replay_prefill_suffix(engine, f)
        elif kind == KIND_CHUNK:
            _replay_chunk(engine, f)
        elif kind == KIND_SLEEP and sleeper is not None:
            sleeper.sleep(int(f["arg"]), release=bool(int(f["arg2"])))
        elif kind == KIND_WAKE and sleeper is not None:
            sleeper.wake_up()


def _sync_mirrors(engine: Any, f: Dict[str, np.ndarray]) -> None:
    engine._last_tokens[:] = f["lt"]
    engine._positions[:] = f["pos"]
    engine._budgets[:] = f["budget"]
    engine._temps[:] = f["temps"]
    engine._topps[:] = f["topps"]
    engine._pres[:] = f["pres"]
    engine._freqs[:] = f["freqs"]
    engine._page_table[:] = f["page_table"]
    engine._slot_keys[:] = f["skeys"]
    engine._eos_on[:] = f["eos_on"]


def _replay_prefill(engine: Any, f: Dict[str, np.ndarray]) -> None:
    bucket = int(f["arg"])
    slot = int(f["arg2"])
    n = int(f["seq_len"])
    _sync_mirrors(engine, f)
    tokens = np.zeros((1, bucket), np.int32)
    tokens[0, :] = f["tokens"][:bucket]
    seq_lens = np.array([n], np.int32)
    table = engine._page_table[slot : slot + 1]
    temp = np.asarray([float(f["temp"])], np.float32)
    topp = np.asarray([float(f["top_p"])], np.float32)
    counts_row = engine._token_counts[slot : slot + 1]
    zero = np.zeros((1,), np.float32)
    fn = (
        engine._prefill_plp_fn
        if int(f.get("want_plp", 0))
        else engine._prefill_fn
    )
    _tok, _lp, _av, _ai, _plp, cache, new_key = fn(
        engine.params,
        tokens,
        seq_lens,
        engine.pool.as_tuple(),
        table,
        temp,
        topp,
        counts_row,
        zero,
        zero,
        engine._slot_keys[slot],
        # biased requests are rejected for gangs; a zero row keeps the
        # program signature
        np.zeros((1, engine.cfg.model.vocab_size), np.float32),
    )
    engine._slot_keys[slot] = np.asarray(new_key)
    engine.pool.replace(cache)
    # no host sync: the leader alone consumes tokens


def _replay_prefill_suffix(engine: Any, f: Dict[str, np.ndarray]) -> None:
    bucket = int(f["arg"])
    slot = int(f["arg2"])
    n = int(f["seq_len"])
    _sync_mirrors(engine, f)
    tokens = np.zeros((1, bucket), np.int32)
    tokens[0, :] = f["tokens"][:bucket]
    start = np.array([int(f["start"])], np.int32)
    suffix_lens = np.array([n], np.int32)
    table = engine._page_table[slot : slot + 1]
    temp = np.asarray([float(f["temp"])], np.float32)
    topp = np.asarray([float(f["top_p"])], np.float32)
    counts_row = engine._token_counts[slot : slot + 1]
    zero = np.zeros((1,), np.float32)
    # targets feed prompt-logprob gathering; followers discard outputs,
    # so zeros keep the program shape without carrying data in the frame
    fn = (
        engine._suffix_prefill_plp_fn
        if int(f.get("want_plp", 0))
        else engine._suffix_prefill_fn
    )
    _tok, _lp, _av, _ai, _plp, cache, new_key = fn(
        engine.params,
        tokens,
        np.zeros_like(tokens),
        start,
        suffix_lens,
        engine.pool.as_tuple(),
        table,
        temp,
        topp,
        counts_row,
        zero,
        zero,
        engine._slot_keys[slot],
        np.zeros((1, engine.cfg.model.vocab_size), np.float32),
    )
    if int(f["advance_key"]):
        engine._slot_keys[slot] = np.asarray(new_key)
    engine.pool.replace(cache)


def _replay_chunk(engine: Any, f: Dict[str, np.ndarray]) -> None:
    T = int(f["arg"])
    if int(f["reupload"]) or engine._dev is None:
        _sync_mirrors(engine, f)
        engine._upload_sched()
    d = engine._dev
    (
        _toks, _lps, _avs, _ais, lt, pos, budget, cache, counts_dev,
        skeys_dev,
    ) = engine._chunk_fn(T)(
        engine.params,
        d["lt"],
        d["pos"],
        d["budget"],
        engine.pool.as_tuple(),
        d["pt"],
        d["temps"],
        d["topp"],
        d["counts"],
        d["pres"],
        d["freq"],
        d["skeys"],
        d["eos_on"],
        d["bias"],
    )
    engine.pool.replace(cache)
    engine._dev = {
        "lt": lt, "pos": pos, "budget": budget,
        "pt": d["pt"], "temps": d["temps"], "topp": d["topp"],
        "counts": counts_dev, "pres": d["pres"], "freq": d["freq"],
        "skeys": skeys_dev, "eos_on": d["eos_on"], "bias": d["bias"],
    }
