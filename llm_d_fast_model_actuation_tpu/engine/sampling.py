"""Token sampling, in-jit (no host round-trip per step).

Greedy when temperature == 0 (selected with `lax.cond`-free arithmetic so the
same compiled fn serves both; temperature is a traced scalar)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample(
    logits: jnp.ndarray,  # [b, vocab] fp32
    key: jax.Array,
    temperature: jnp.ndarray,  # [b] fp32; 0 = greedy
    top_k: int = 0,  # static; 0 = no truncation
) -> jnp.ndarray:
    if top_k > 0 and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    greedy = jnp.argmax(logits, axis=-1)
    t = jnp.maximum(temperature, 1e-6)[:, None]
    sampled = jax.random.categorical(key, logits / t, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)
