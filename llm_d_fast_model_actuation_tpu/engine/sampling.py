"""Token sampling, in-jit (no host round-trip per step).

Greedy when temperature == 0 (selected with `lax.cond`-free arithmetic so
the same compiled fn serves both; temperature is a traced scalar).
Per-request nucleus (top-p) sampling runs over the top-`candidates`
logits — the standard serving approximation (p mass outside the top 64
is negligible for real models) — selected per row by `top_p < 1`, again
branch-free. The sampled token's logprob (full-vocab normalized) is
returned alongside, so the API can serve OpenAI `logprobs` for free.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: nucleus sampling truncates to this many candidates before the cumsum
TOP_P_CANDIDATES = 64


def sample(
    logits: jnp.ndarray,  # [b, vocab] fp32
    key: jax.Array,  # scalar key, or [b] per-row keys (per-request seeds)
    temperature: jnp.ndarray,  # [b] fp32; 0 = greedy
    top_p: "jnp.ndarray | None" = None,  # [b] fp32; >= 1 = full distribution
    top_k: int = 0,  # static; 0 = no truncation
    counts: "jnp.ndarray | None" = None,  # [b, vocab] int32 token counts
    presence_penalty: "jnp.ndarray | None" = None,  # [b] fp32
    frequency_penalty: "jnp.ndarray | None" = None,  # [b] fp32
    alt_k: int = 0,  # static; also return the top-k alternative logprobs
    bias: "jnp.ndarray | None" = None,  # [b, vocab] fp32 logit bias
):
    """Returns (token [b] int32, logprob [b] fp32 of the chosen token) —
    plus, when `alt_k > 0`, (alt_logprobs [b, alt_k] fp32,
    alt_ids [b, alt_k] int32): the top-k of the same raw distribution the
    reported logprob comes from (OpenAI `logprobs`/`top_logprobs`).

    OpenAI-order transform chain: repetition penalties (subtract
    freq*count + pres*[count>0] from the logits) -> temperature ->
    top-p truncation. Penalties shift greedy decoding too. The reported
    logprob is OpenAI-style "raw": normalized over the penalized (and
    top-k-truncated) logits BEFORE temperature scaling and top-p
    truncation — for temperature != 1 or top_p < 1 it is not the exact
    distribution the token was drawn from."""
    if bias is not None:
        # OpenAI logit_bias: added before everything else, so it shifts
        # greedy decoding, the reported logprobs, and the alternatives
        logits = logits + bias
    if counts is not None:
        cf = counts.astype(jnp.float32)
        pen = jnp.zeros_like(logits)
        if frequency_penalty is not None:
            pen = pen + frequency_penalty[:, None] * cf
        if presence_penalty is not None:
            pen = pen + presence_penalty[:, None] * (cf > 0)
        logits = logits - pen
    if top_k > 0 and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    norm = logits - jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)
    greedy = jnp.argmax(logits, axis=-1)
    t = jnp.maximum(temperature, 1e-6)[:, None]
    per_row = getattr(key, "ndim", 0) == 1  # [b] per-request keys
    if per_row:
        key_full, key_nuc = jax.vmap(
            lambda k: tuple(jax.random.split(k))
        )(key)
        sampled = jax.vmap(
            lambda k, row: jax.random.categorical(k, row)
        )(key_full, logits / t)
    else:
        key_full, key_nuc = jax.random.split(key)
        sampled = jax.random.categorical(key_full, logits / t, axis=-1)
    if top_p is not None:
        c = min(TOP_P_CANDIDATES, logits.shape[-1])
        vals, idx = jax.lax.top_k(logits, c)  # [b, c] descending
        # nucleus membership over the TEMPERED distribution (OpenAI/vLLM
        # order: temperature first, then top-p truncation)
        probs = jax.nn.softmax(vals / t, axis=-1)
        csum = jnp.cumsum(probs, axis=-1)
        # keep tokens whose PRECEDING mass is < p (the first is always kept)
        keep = (csum - probs) < top_p[:, None]
        masked = jnp.where(keep, vals, -jnp.inf)
        if per_row:
            choice = jax.vmap(
                lambda k, row: jax.random.categorical(k, row)
            )(key_nuc, masked / t)
        else:
            choice = jax.random.categorical(key_nuc, masked / t, axis=-1)
        nucleus = jnp.take_along_axis(idx, choice[:, None], axis=-1)[:, 0]
        sampled = jnp.where(top_p < 1.0, nucleus, sampled)
    tok = jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)
    lp = jnp.take_along_axis(norm, tok[:, None].astype(jnp.int32), axis=-1)[:, 0]
    if alt_k > 0:
        alt_lps, alt_ids = jax.lax.top_k(norm, alt_k)
        return tok, lp, alt_lps, alt_ids.astype(jnp.int32)
    return tok, lp
