"""Bindings to the native (C++) components under the repo's `native/` tree."""
