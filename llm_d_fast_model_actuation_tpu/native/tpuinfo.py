"""ctypes binding to the C++ chip-telemetry shim (`native/tpuinfo/`).

The reference delegates accelerator identity/telemetry to NVML/`nvidia-smi`;
there is no TPU equivalent of "nvidia-smi for another process's HBM", so this
shim is authored natively (SURVEY.md §2.9, §7): chip enumeration from the PCI
tree / devfs and per-chip HBM usage where the runtime exposes it.

The shared library is looked up at $FMA_TPUINFO_LIB, next to this file, or in
the repo's native/build directory. All entry points raise RuntimeError when
the shim isn't built — callers (ChipTranslator, requester) treat that as
"fall back to mock/devfs".
"""

from __future__ import annotations

import ctypes
import json
import os
from typing import Dict, List, Optional

_LIB = None
_SEARCH = (
    os.environ.get("FMA_TPUINFO_LIB", ""),
    os.path.join(os.path.dirname(__file__), "libtpuinfo.so"),
    os.path.join(
        os.path.dirname(__file__), "..", "..", "native", "build", "libtpuinfo.so"
    ),
)


def _lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is None:
        for path in _SEARCH:
            if path and os.path.exists(path):
                lib = ctypes.CDLL(path)
                lib.tpuinfo_query.restype = ctypes.c_void_p
                lib.tpuinfo_query.argtypes = []
                lib.tpuinfo_free.restype = None
                lib.tpuinfo_free.argtypes = [ctypes.c_void_p]
                _LIB = lib
                break
        else:
            raise RuntimeError("libtpuinfo.so not built")
    return _LIB


def _query() -> Dict:
    lib = _lib()
    ptr = lib.tpuinfo_query()
    if not ptr:
        raise RuntimeError("tpuinfo_query returned NULL")
    try:
        raw = ctypes.string_at(ptr)
    finally:
        lib.tpuinfo_free(ptr)
    return json.loads(raw.decode())


def enumerate_chips() -> List[Dict]:
    """[{chip_id, index, coords?, total_hbm_bytes?}] for local TPU chips."""
    return _query().get("chips", [])


def host_topology() -> Optional[str]:
    return _query().get("topology") or None


def hbm_usage() -> Dict[str, int]:
    """chip_id -> bytes of HBM in use (0 when the runtime hides it)."""
    return {
        c["chip_id"]: int(c.get("hbm_used_bytes", 0))
        for c in _query().get("chips", [])
    }


def main(argv: Optional[List[str]] = None) -> int:
    """CLI for chip-map probe pods (`python -m ...native.tpuinfo --table`):
    prints the ChipMap line grammar the controller parses — the tpuinfo
    analogue of the reference probe pods' `nvidia-smi --query-gpu=index,uuid`
    (scripts/ensure-nodes-mapped.sh)."""
    import argparse

    p = argparse.ArgumentParser(prog="fma-tpuinfo")
    p.add_argument(
        "--table",
        action="store_true",
        help="chip-map grammar: 'topology: TxU' then '<index> <chip_id> <x,y>'",
    )
    args = p.parse_args(argv)
    if args.table:
        topo = host_topology()
        if topo:
            print(f"topology: {topo}")
        # Multi-host slice identity (parallel/multihost.py plans gangs from
        # these): FMA_HOST_ORIGIN/FMA_SLICE_ID override; else derive the
        # origin from the libtpu worker index (v5e multi-host slices tile
        # hosts along the first axis) and the slice id from TPU_NAME.
        origin = os.environ.get("FMA_HOST_ORIGIN", "")
        if not origin and topo:
            wid = os.environ.get("TPU_WORKER_ID", "")
            if wid.isdigit() and int(wid) > 0:
                dims = [int(d) for d in topo.split("x")]
                o = [0] * len(dims)
                o[0] = int(wid) * dims[0]
                origin = ",".join(str(x) for x in o)
        slice_id = os.environ.get(
            "FMA_SLICE_ID", os.environ.get("TPU_NAME", "")
        )
        if origin:
            print(f"origin: {origin}")
        if slice_id:
            print(f"slice: {slice_id}")
        for c in sorted(enumerate_chips(), key=lambda c: int(c["index"])):
            coords = ",".join(str(x) for x in (c.get("coords") or []))
            print(f"{c['index']} {c['chip_id']} {coords}".rstrip())
    else:
        print(json.dumps(_query(), indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
