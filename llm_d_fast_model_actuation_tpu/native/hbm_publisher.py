"""Engine side of the cooperative HBM-usage protocol.

The TPU runtime — unlike NVML (reference:
pkg/server/requester/coordination/server.go:100, which reads another
process's GPU memory via `nvidia-smi`) — exposes no cross-process device
memory query. So usage telemetry is cooperative: each engine process
publishes its live per-chip HBM byte count as a decimal string at

    $FMA_TPUINFO_USAGE_DIR/<chip_id>/<pid>     (default /run/fma-tpu/hbm)

and the native shim (`native/tpuinfo/tpuinfo.cpp`) sums live writers per
chip, pruning files of dead pids. The requester SPI's accelerator-memory
query and the controller's pre-wake budget check then work exactly like the
reference's NVML path.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable

DEFAULT_USAGE_DIR = "/run/fma-tpu/hbm"


def usage_dir() -> str:
    return os.environ.get("FMA_TPUINFO_USAGE_DIR", DEFAULT_USAGE_DIR)


class HbmUsagePublisher:
    """Publishes this process's per-chip HBM usage; one file per chip."""

    def __init__(self, chip_ids: Iterable[str], root: str | None = None) -> None:
        self._chip_ids = list(chip_ids)
        self._root = root or usage_dir()
        self._pid = os.getpid()

    def set(self, bytes_by_chip: Dict[str, int]) -> None:
        for chip_id in self._chip_ids:
            path = os.path.join(self._root, chip_id)
            try:
                os.makedirs(path, exist_ok=True)
                tmp = os.path.join(path, f".{self._pid}.tmp")
                with open(tmp, "w") as f:
                    f.write(str(int(bytes_by_chip.get(chip_id, 0))))
                os.replace(tmp, os.path.join(path, str(self._pid)))
            except OSError:
                pass  # telemetry is best-effort; never fail the engine for it

    def set_uniform(self, total_bytes: int) -> None:
        """Spread `total_bytes` evenly over this engine's chips (the common
        case: SPMD-sharded state uses the same HBM on every chip)."""
        n = max(1, len(self._chip_ids))
        self.set({cid: total_bytes // n for cid in self._chip_ids})

    def clear(self) -> None:
        for chip_id in self._chip_ids:
            try:
                os.unlink(os.path.join(self._root, chip_id, str(self._pid)))
            except OSError:
                pass
