"""Shared utilities: hashing/identity, async event fan-out, ranged logs, metrics."""

from .hashing import canonical_json, instance_id_for, sha256_hex  # noqa: F401
from .events import EventBroadcaster, RevisionTooOld  # noqa: F401
