"""Deterministic identity hashing.

The reference derives several load-bearing identities from content hashes:
  * instance ID = "I" + base64url(SHA-256(ModelServerConfig YAML + gpus)) + "i"
    (inference-server.go:1015-1057) — same config + same accelerators on a
    different day must produce the same instance, enabling the wake fast path;
  * nominal-provider hash = SHA-256(patched pod JSON + gpus + node)
    (inference-server.go:1880-1888);
  * launcher template hash over a canonicalized (order-independent) template
    (pod-helper.go:143-197).

Here all hashes run over canonical JSON (sorted keys, no whitespace drift).
"""

from __future__ import annotations

import base64
import hashlib
import json
from typing import Any, Iterable, Sequence


def canonical_json(obj: Any) -> str:
    """Deterministic JSON: sorted keys, compact separators."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"), default=str)


def sha256_hex(*parts: str) -> str:
    h = hashlib.sha256()
    for p in parts:
        h.update(p.encode())
        h.update(b"\x00")
    return h.hexdigest()


def instance_id_for(
    engine_config: Any,
    chip_ids: Sequence[str],
    extra_env: Any = None,
) -> str:
    """Deterministic engine-instance ID from (config, chip set).

    Format "I<base64url(sha256)>i" — the reference's shape
    (inference-server.go:1030-1045); base64url keeps it label-safe.
    Chip order is normalized: the same chips in any order are the same
    instance.

    `extra_env` (the slice-gang coordination env, which includes the unique
    gang id) is hashed in when present: a process that joined one
    jax.distributed gang can never serve another (initialize cannot re-run
    in-process), so instances of different gangs must never be identified —
    a sleeping member of a dead gang is left for reclaim, not woken.
    `None` keeps single-host IDs identical to the pre-gang scheme.
    """
    cfg = engine_config.to_dict() if hasattr(engine_config, "to_dict") else engine_config
    body = {"config": cfg, "chips": sorted(chip_ids)}
    if extra_env:
        body["gang_env"] = dict(extra_env)
    payload = canonical_json(body)
    digest = hashlib.sha256(payload.encode()).digest()
    return "I" + base64.urlsafe_b64encode(digest).decode().rstrip("=") + "i"


def nominal_hash(pod_like: Any, chip_ids: Iterable[str], node: str) -> str:
    """Identity of a direct-path nominal providing Pod."""
    return sha256_hex(canonical_json(pod_like), canonical_json(sorted(chip_ids)), node)


def canonicalize_for_hash(obj: Any) -> Any:
    """Canonicalize a pod-template-shaped dict for stable hashing: sort
    order-independent list fields (env, volumes, ...) by name; recurse.

    Reference: canonicalizeTemplateForHash (pod-helper.go:143-197).
    """
    if isinstance(obj, dict):
        out = {}
        for k, v in obj.items():
            v = canonicalize_for_hash(v)
            if isinstance(v, list) and v and all(
                isinstance(e, dict) and "name" in e for e in v
            ):
                v = sorted(v, key=lambda e: e["name"])
            out[k] = v
        return out
    if isinstance(obj, list):
        return [canonicalize_for_hash(e) for e in obj]
    return obj


def template_hash(template: Any) -> str:
    """Order-independent hash of a launcher Pod template."""
    return sha256_hex(canonical_json(canonicalize_for_hash(template)))
