"""KnowsProcessedSync: the initial-batch rendezvous.

The reference's knows-processed-sync.go:27-103 lets callers wait until every
object that existed at controller start has been through one processing
pass — acting on a partially-processed world (e.g. deleting "excess"
launchers before having seen all of them) is how controllers eat their own
state. Our kube store relists before watching, so the *cache* is complete at
start; this barrier tracks the *processing* side: each initially-enqueued
key is noted, `arm()` closes the initial set, and the event fires when the
last of them completes its first pass (success or retry — the barrier is
about having LOOKED at everything once, not about convergence).

Used as the controllers' readiness signal: a controller that has processed
its initial batch knows enough to be trusted with destructive decisions.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Hashable, Set


class KnowsProcessedSync:
    def __init__(self) -> None:
        self._pending: Set[Hashable] = set()
        self._armed = False
        self._lock = threading.Lock()
        self._event = asyncio.Event()

    def note_pending(self, key: Hashable) -> None:
        """Record an initially-enqueued key. No-op once armed (keys arriving
        after arm() are live events, not initial state)."""
        with self._lock:
            if not self._armed:
                self._pending.add(key)

    def arm(self) -> None:
        """Close the initial set; the event fires when it drains."""
        with self._lock:
            self._armed = True
        self._maybe_fire()

    def note_processed(self, key: Hashable) -> None:
        with self._lock:
            self._pending.discard(key)
        self._maybe_fire()

    def _maybe_fire(self) -> None:
        with self._lock:
            done = self._armed and not self._pending
        if done:
            self._event.set()

    @property
    def processed(self) -> bool:
        return self._event.is_set()

    async def wait(self, timeout: float = 0.0) -> None:
        if timeout:
            await asyncio.wait_for(self._event.wait(), timeout)
        else:
            await self._event.wait()
