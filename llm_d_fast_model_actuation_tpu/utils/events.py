"""Bounded asyncio event fan-out with revisions.

The launcher's watch endpoint speaks kube-watch semantics: every event
carries a monotonically increasing revision; a watcher resuming from a
revision that has been evicted from the buffer gets `RevisionTooOld`
(HTTP 410 Gone), telling it to re-list and re-watch.

Reference: EventBroadcaster, launcher.py:87-146.
"""

from __future__ import annotations

import asyncio
from collections import deque
from typing import Any, AsyncIterator, Deque, Tuple


class RevisionTooOld(Exception):
    """The requested resume revision predates the retained buffer."""


class EventBroadcaster:
    def __init__(self, max_buffer: int = 1000) -> None:
        self._buf: Deque[Tuple[int, Any]] = deque(maxlen=max_buffer)
        self._cond: asyncio.Condition | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._closed = False

    def _condition(self) -> asyncio.Condition:
        # Lazily bound to the running loop (the broadcaster may be built
        # before the event loop starts).
        if self._cond is None:
            self._cond = asyncio.Condition()
            self._loop = asyncio.get_running_loop()
        return self._cond

    @property
    def oldest_revision(self) -> int | None:
        return self._buf[0][0] if self._buf else None

    @property
    def latest_revision(self) -> int | None:
        return self._buf[-1][0] if self._buf else None

    async def publish(self, revision: int, event: Any) -> None:
        cond = self._condition()
        async with cond:
            self._buf.append((revision, event))
            cond.notify_all()

    def publish_nowait(self, revision: int, event: Any) -> None:
        """Publish from synchronous code — on the loop's thread OR any other
        thread (e.g. an executor running a blocking instance stop). Watchers
        are woken via the loop the condition is bound to."""
        self._buf.append((revision, event))
        cond, loop = self._cond, self._loop
        if cond is None or loop is None:
            return  # no watcher loop yet: they'll see it on first subscribe

        async def _notify() -> None:
            async with cond:
                cond.notify_all()

        def _schedule() -> None:
            loop.create_task(_notify())

        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is loop:
            _schedule()
        else:
            try:
                loop.call_soon_threadsafe(_schedule)
            except RuntimeError:
                # bound loop already closed (shutdown): the event stays in the
                # buffer; there is no watcher loop left to wake
                pass

    async def close(self) -> None:
        cond = self._condition()
        async with cond:
            self._closed = True
            cond.notify_all()

    async def subscribe(self, since_revision: int = 0) -> AsyncIterator[Any]:
        """Yield events with revision > since_revision, forever (until close).

        Raises RevisionTooOld if `since_revision` is older than the oldest
        retained event (and not simply "from the beginning of retention").
        """
        cursor = since_revision
        cond = self._condition()
        while True:
            async with cond:
                oldest = self.oldest_revision
                if (
                    cursor
                    and oldest is not None
                    and cursor < oldest - 1
                ):
                    raise RevisionTooOld(
                        f"revision {cursor} evicted (oldest retained {oldest})"
                    )
                # snapshot first: publish_nowait may append from another
                # thread while we iterate
                snapshot = list(self._buf)
                pending = [e for (rev, e) in snapshot if rev > cursor]
                newest = snapshot[-1][0] if snapshot else None
                if not pending:
                    if self._closed:
                        return
                    await cond.wait()
                    continue
            for e in pending:
                yield e
            cursor = max(cursor, newest or cursor)
