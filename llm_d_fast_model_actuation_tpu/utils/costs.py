"""Actuation cost oracle substrate: bandwidth EWMAs + decision flight
recorder.

The SLO-aware scheduler (ROADMAP item 1) needs the *cost* half of its
sensor substrate: "what will this sleep/wake/swap/prefetch cost in
seconds and bytes, priced BEFORE moving anything". Bytes are exactly
predictable pre-transfer (chunk-store digests make a sibling swap's
delta deterministic, ``models/quant.payload_nbytes`` sizes compressed
payloads from shapes alone); seconds need a measured bandwidth model.
This module holds the two pieces every engine keeps:

  * :class:`BandwidthEWMA` / :class:`BandwidthBook` — per-transfer-kind
    exponentially-decayed GiB/s estimates (``swap.d2h``, ``swap.h2d``,
    ``swap.total`` — the whole-verb effective rate pool-hit pricing
    prefers — ``wake.h2d``, ``sleep.d2h``, ``coldload.read``,
    ``coldload.h2d``, ``coresident.h2d`` (the delta-only upload a
    variant attach streams), ``migrate.export`` / ``migrate.import``
    (a live-migration parked bundle's wire serialization and its
    destination-side page-in), and ``quant.dequant``, the non-hidden
    on-device expansion tail of compressed transfers),
    fed by the byte/time figures the transfer paths already compute
    (engine/sleep.py, models/hf.py) and surviving across actuations in
    ``EngineService``. A kind with no history falls back first to any
    same-direction kind, then to a conservative constant — always
    flagged ``measured: false`` so a consumer knows to distrust it.

  * :class:`FlightRecorder` — a bounded ring of structured
    :class:`ActuationRecord` rows, one per actuation: kind, model,
    trigger, tier, predicted vs actual bytes/seconds, relative error,
    outcome. Served by engine ``GET /v1/actuations`` and summarized into
    ``GET /v1/stats`` (the launcher's fleet rollup carries it into the
    ``ledger.costs`` block) — the scheduler's decision audit trail, and
    the oracle's own accuracy score.

Mirrors utils/tracing.py's discipline: stdlib only, bounded memory,
thread-safe, never raises into an actuation path.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

#: conservative cold-start GiB/s per direction family — used only before
#: the first measured transfer of any kind in that family, and always
#: reported with ``measured: false`` (docs/operations.md "Pricing an
#: actuation"). Deliberately low-ball: over-predicting seconds makes a
#: scheduler conservative, never late.
DEFAULT_GIBPS: Dict[str, float] = {
    "d2h": 1.0,
    "h2d": 1.0,
    "read": 0.5,
}
_FALLBACK_GIBPS = 1.0

#: default flight-recorder capacity (records, not bytes — each is a
#: small dict); overridable via FMA_FLIGHT_RECORDER_CAP
DEFAULT_RECORDER_CAPACITY = 512


def kind_family(kind: str) -> str:
    """Direction family of a transfer kind: ``"swap.d2h" -> "d2h"`` —
    the fallback bucket when the exact kind has no history yet."""
    return kind.rsplit(".", 1)[-1]


class BandwidthEWMA:
    """Exponentially-decayed GiB/s estimate for one transfer kind.

    Each observation contributes weight 1; all prior weight decays by
    ``exp(-dt / tau_s) * obs_decay`` — exponential in elapsed time AND
    in observation count — so the estimate is a weighted mean dominated
    by recent transfers. That double decay is deliberate: the first
    transfer of a kind often carries one-time costs (jit compiles of the
    quantize/dequantize ops, cache population) that would anchor a
    plain mean low forever, and a backend change (new link, new host)
    must re-converge within a few actuations. Reading is side-effect
    free (numerator and denominator decay together, so the ratio is
    time-invariant between observations)."""

    def __init__(
        self, tau_s: float = 600.0, obs_decay: float = 0.35
    ) -> None:
        self.tau_s = max(1e-6, float(tau_s))
        self.obs_decay = min(1.0, max(0.0, float(obs_decay)))
        self._weight = 0.0
        self._weighted_gibps = 0.0
        self._t: Optional[float] = None
        self.samples = 0
        self.last_gibps = 0.0

    def observe(
        self, nbytes: int, seconds: float, now: Optional[float] = None
    ) -> None:
        if nbytes <= 0 or seconds <= 0:
            return
        g = (nbytes / 2**30) / seconds
        t = time.monotonic() if now is None else now
        decay = self.obs_decay
        if self._t is not None and t > self._t:
            decay *= math.exp(-(t - self._t) / self.tau_s)
        self._weight *= decay
        self._weighted_gibps *= decay
        self._t = t
        self._weight += 1.0
        self._weighted_gibps += g
        self.samples += 1
        self.last_gibps = g

    def gibps(self) -> Optional[float]:
        if self._weight <= 0:
            return None
        return self._weighted_gibps / self._weight


class BandwidthBook:
    """Per-kind :class:`BandwidthEWMA` registry with direction-family
    fallback — one instance per engine process, fed by every actuation
    transfer. Thread-safe (observations come from the engine/admin
    threads, reads from HTTP executor threads)."""

    def __init__(self, tau_s: float = 600.0) -> None:
        self.tau_s = tau_s
        self._mu = threading.Lock()
        self._kinds: Dict[str, BandwidthEWMA] = {}

    def observe(self, kind: str, nbytes: int, seconds: float) -> None:
        with self._mu:
            ew = self._kinds.get(kind)
            if ew is None:
                ew = self._kinds[kind] = BandwidthEWMA(self.tau_s)
            ew.observe(nbytes, seconds)

    def has(self, kind: str) -> bool:
        """True when `kind` itself has measured history (no family
        fallback considered)."""
        with self._mu:
            ew = self._kinds.get(kind)
            return ew is not None and ew.samples > 0

    def estimate(self, kind: str) -> Tuple[float, bool, str]:
        """``(gibps, measured, source)`` for `kind`: the kind's own EWMA
        when it has history; else the best-sampled same-family kind
        (``measured`` stays True — same direction, same link); else the
        conservative :data:`DEFAULT_GIBPS` constant with ``measured``
        False."""
        fam = kind_family(kind)
        with self._mu:
            ew = self._kinds.get(kind)
            if ew is not None and ew.samples > 0:
                return float(ew.gibps()), True, kind
            best: Optional[Tuple[str, BandwidthEWMA]] = None
            for k, cand in self._kinds.items():
                if kind_family(k) != fam or cand.samples <= 0:
                    continue
                if best is None or cand.samples > best[1].samples:
                    best = (k, cand)
            if best is not None:
                return float(best[1].gibps()), True, best[0]
        return DEFAULT_GIBPS.get(fam, _FALLBACK_GIBPS), False, "default"

    def seconds_for(self, kind: str, nbytes: int) -> Tuple[float, bool]:
        """Predicted seconds to move `nbytes` on the `kind` path, and
        whether the bandwidth behind it was measured."""
        gibps, measured, _ = self.estimate(kind)
        return (max(0, nbytes) / 2**30) / max(1e-9, gibps), measured

    def describe(self) -> Dict[str, Dict[str, Any]]:
        with self._mu:
            return {
                k: {
                    "gibps": round(ew.gibps() or 0.0, 6),
                    "last_gibps": round(ew.last_gibps, 6),
                    "samples": ew.samples,
                }
                for k, ew in self._kinds.items()
            }


@dataclass
class ActuationRecord:
    """One flight-recorder row: what the scheduler decided to move, what
    the oracle priced it at, and what it actually cost."""

    seq: int
    t_wall: float  #: unix seconds at record time (the ring is ordered)
    kind: str  #: swap | sleep | wake | coldload | prefetch | attach | detach | migrate
    model: str
    trigger: str  #: client | restart | escalation | startup
    #: where the moved state lived / went: pool | prefetched | host |
    #: disk | cold | resident | coresident (a sibling variant sharing
    #: the live base's device tensors) | discard (an L2 sleep drops the
    #: host copy) | "" (unknown, e.g. a failed swap priced before any
    #: tier resolved)
    tier: str
    outcome: str  #: committed | rolled_back | failed
    actual_bytes: int = 0
    actual_s: float = 0.0
    predicted_bytes: Optional[int] = None
    predicted_s: Optional[float] = None
    #: prediction based on measured bandwidth (False = cold-start
    #: constant fallback — distrust the seconds figure)
    measured: bool = False
    #: signed (predicted - actual) / actual; None when unpredicted or
    #: the actual is zero
    bytes_error_ratio: Optional[float] = None
    seconds_error_ratio: Optional[float] = None
    #: structured per-actuation context; zero-drain actuations record
    #: ``preempted`` / ``resumed`` request counts here, so
    #: GET /v1/actuations shows what each swap displaced
    extra: Dict[str, Any] = field(default_factory=dict)

    def as_dict(self) -> Dict[str, Any]:
        out = {
            "seq": self.seq,
            "t_wall": round(self.t_wall, 6),
            "kind": self.kind,
            "model": self.model,
            "trigger": self.trigger,
            "tier": self.tier,
            "outcome": self.outcome,
            "actual_bytes": int(self.actual_bytes),
            "actual_s": round(self.actual_s, 6),
            "predicted_bytes": (
                None if self.predicted_bytes is None
                else int(self.predicted_bytes)
            ),
            "predicted_s": (
                None if self.predicted_s is None
                else round(self.predicted_s, 6)
            ),
            "measured": bool(self.measured),
            "bytes_error_ratio": (
                None if self.bytes_error_ratio is None
                else round(self.bytes_error_ratio, 6)
            ),
            "seconds_error_ratio": (
                None if self.seconds_error_ratio is None
                else round(self.seconds_error_ratio, 6)
            ),
        }
        if self.extra:
            out["extra"] = dict(self.extra)
        return out


def _rel_error(
    predicted: Optional[float], actual: float
) -> Optional[float]:
    if predicted is None or actual <= 0:
        return None
    return (float(predicted) - float(actual)) / float(actual)


class FlightRecorder:
    """Bounded ring of :class:`ActuationRecord` rows (oldest dropped).

    ``record(...)`` computes the prediction error ratios; ``records()``
    returns dict rows oldest-first; ``summary(last_n)`` scores the
    oracle over the most recent predicted records — what ``GET
    /v1/stats`` serves and the fleet harness reads."""

    def __init__(self, capacity: int = DEFAULT_RECORDER_CAPACITY) -> None:
        self.capacity = max(1, int(capacity))
        self._mu = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0
        self.total_recorded = 0

    def __len__(self) -> int:
        with self._mu:
            return len(self._ring)

    def record(
        self,
        kind: str,
        model: str,
        trigger: str = "client",
        tier: str = "",
        outcome: str = "committed",
        actual_bytes: int = 0,
        actual_s: float = 0.0,
        predicted_bytes: Optional[int] = None,
        predicted_s: Optional[float] = None,
        measured: bool = False,
        extra: Optional[Dict[str, Any]] = None,
    ) -> ActuationRecord:
        with self._mu:
            self._seq += 1
            rec = ActuationRecord(
                seq=self._seq,
                t_wall=time.time(),
                kind=kind,
                model=model,
                trigger=trigger,
                tier=tier,
                outcome=outcome,
                actual_bytes=int(actual_bytes),
                actual_s=float(actual_s),
                predicted_bytes=predicted_bytes,
                predicted_s=predicted_s,
                measured=measured,
                bytes_error_ratio=_rel_error(
                    predicted_bytes, float(actual_bytes)
                ),
                seconds_error_ratio=_rel_error(predicted_s, actual_s),
                extra=dict(extra or {}),
            )
            self._ring.append(rec)
            self.total_recorded += 1
            return rec

    def records(
        self, n: int = 0, kind: Optional[str] = None
    ) -> List[Dict[str, Any]]:
        with self._mu:
            rows = list(self._ring)
        if kind:
            rows = [r for r in rows if r.kind == kind]
        if n and n > 0:
            rows = rows[-n:]
        return [r.as_dict() for r in rows]

    def summary(self, last_n: int = 32) -> Dict[str, Any]:
        """Oracle accuracy over the last `last_n` records: how many were
        priced, the byte-exact fraction (delta/quant byte prediction is
        deterministic — anything below 1.0 means digests drifted), and
        the mean/max absolute seconds error ratio."""
        with self._mu:
            rows = list(self._ring)[-max(1, last_n):]
            total = self.total_recorded
        by_kind: Dict[str, int] = {}
        for r in rows:
            by_kind[r.kind] = by_kind.get(r.kind, 0) + 1
        # only COMMITTED actuations score byte exactness: a rolled-back
        # or failed swap recorded actual_bytes=0 against a real
        # prediction, and counting it as a miss would read as digest
        # drift (the signal byte_exact_frac exists to expose)
        priced = [
            r
            for r in rows
            if r.predicted_bytes is not None and r.outcome == "committed"
        ]
        byte_exact = sum(
            1 for r in priced if r.predicted_bytes == r.actual_bytes
        )
        sec_errors = [
            abs(r.seconds_error_ratio)
            for r in rows
            if r.seconds_error_ratio is not None and r.measured
        ]
        out: Dict[str, Any] = {
            "recorded_total": total,
            "window": len(rows),
            "by_kind": by_kind,
            "priced": len(priced),
            "byte_exact": byte_exact,
            "byte_exact_frac": (
                round(byte_exact / len(priced), 6) if priced else None
            ),
            "seconds_error_judged": len(sec_errors),
            "mean_abs_seconds_error_ratio": (
                round(sum(sec_errors) / len(sec_errors), 6)
                if sec_errors
                else None
            ),
            "max_abs_seconds_error_ratio": (
                round(max(sec_errors), 6) if sec_errors else None
            ),
        }
        if rows:
            out["last"] = rows[-1].as_dict()
        return out


class CostBook:
    """The one cost-oracle object an :class:`EngineService` owns: the
    bandwidth book plus the flight recorder, with the transfer-path
    callback (`observe_transfer`) the sleep/load machinery feeds."""

    def __init__(
        self,
        capacity: int = DEFAULT_RECORDER_CAPACITY,
        tau_s: float = 600.0,
    ) -> None:
        self.bandwidths = BandwidthBook(tau_s=tau_s)
        self.recorder = FlightRecorder(capacity=capacity)

    def observe_transfer(
        self, kind: str, nbytes: int, seconds: float
    ) -> None:
        """The byte/time figure callback every transfer path reports
        through (engine/sleep.py on_transfer, models/hf.py
        LoadStats.transfer_figures). Never raises — telemetry must not
        fail an actuation."""
        try:
            self.bandwidths.observe(kind, nbytes, seconds)
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            pass

    def record(self, **kw: Any) -> ActuationRecord:
        return self.recorder.record(**kw)

    def summary(self, last_n: int = 32) -> Dict[str, Any]:
        return {
            "bandwidth_gibps": self.bandwidths.describe(),
            "prediction": self.recorder.summary(last_n=last_n),
        }
