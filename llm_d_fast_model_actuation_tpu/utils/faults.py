"""Fault-injection registry: named failure points on the actuation paths.

The self-healing layer (transactional swap rollback, supervised engine
restart, retried launcher RPC) is only trustworthy if its failure paths are
deterministically testable — "unplug the cable" cannot be a unit test. This
module gives every recovery-relevant transfer edge a *named injection
point*; production code calls :func:`fire` at the edge, which is a no-op
until a test (or an operator running a fault drill) arms the point.

Wired points (the canonical set; arbitrary names are accepted so tests can
add their own):

  ==================  =====================================================
  ``swap.d2h``        hot-swap outgoing bucket issue (engine/sleep.py)
  ``swap.h2d``        hot-swap incoming bucket issue (engine/sleep.py)
  ``kvsave.d2h``      zero-drain park: live-KV page-out chunk (engine/parked.py)
  ``kvrestore.h2d``   zero-drain resume: KV page-in chunk (engine/parked.py)
  ``migrate.export``  migration export: bundle serialization after the park
                      (engine/server.py; recovery = local resume)
  ``migrate.import``  migration import: before the destination seats anything
                      (engine/server.py; recovery = clean rollback)
  ``migrate.ack``     migration import ack lost after a successful seat
                      (engine/server.py; recovery = fenced idempotent retry)
  ``coldload.read``   cold HF shard read start (models/hf.py)
  ``coldload.h2d``    cold-load / staged-placement H2D bucket (models/hf.py)
  ``prefetch.stage``  background prefetch staging start (engine/server.py)
  ``launcher.rpc``    launcher -> engine-child admin RPC (launcher/manager.py)
  ``instance.spawn``  supervised restart spawning the child (launcher/manager.py)
  ==================  =====================================================

Modes (per point): **fail** raises :class:`FaultError` the next ``count``
times the point fires (fail-once is ``count=1``, fail-N is ``count=N``,
``count=-1`` is every time); **delay** sleeps ``delay_s`` seconds for the
next ``count`` firings (default: every time) — the slow-link / slow-bind
simulator.

Arming surfaces (all equivalent):
  * env var ``FMA_FAULTS`` — loaded by the engine service and the launcher
    at startup (forked engine children inherit it via instance env_vars);
  * engine flag ``--faults "<spec>"``;
  * REST — engine ``/v1/faults``, launcher ``/v2/vllm/faults``
    (GET describe / POST arm / DELETE reset).

Spec grammar (comma-separated): ``point=fail`` | ``point=fail:N`` |
``point=delay:SECONDS`` | ``point=delay:SECONDS:N``, e.g.
``FMA_FAULTS="swap.h2d=fail:1,coldload.read=delay:0.25"``.

The registry is process-global and thread-safe; state armed pre-fork is
inherited by forked children (the launcher's process model).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional

#: the points production code is wired to fire (documentation + describe())
KNOWN_POINTS = (
    "swap.d2h",
    "swap.h2d",
    "kvsave.d2h",
    "kvrestore.h2d",
    "migrate.export",
    "migrate.import",
    "migrate.ack",
    "coldload.read",
    "coldload.h2d",
    "prefetch.stage",
    "launcher.rpc",
    "instance.spawn",
)

ENV_VAR = "FMA_FAULTS"


class FaultError(RuntimeError):
    """The injected failure (mode=fail). Deliberately a plain RuntimeError
    subclass: recovery code must handle it exactly like a real transfer /
    RPC / spawn failure, never special-case it."""

    def __init__(self, point: str) -> None:
        super().__init__(f"injected fault at {point!r}")
        self.point = point


@dataclass
class _Armed:
    mode: str  # "fail" | "delay"
    remaining: int  # firings left to act on; -1 = unbounded
    delay_s: float = 0.0
    fired: int = 0  # times this point acted (raised or slept)


def _parse_one(item: str) -> tuple:
    """``point=mode[:arg[:count]]`` -> (point, _Armed); ValueError on junk."""
    point, sep, rhs = item.partition("=")
    point = point.strip()
    if not sep or not point or not rhs.strip():
        raise ValueError(f"bad fault spec {item!r} (want point=mode[:...])")
    parts = [p.strip() for p in rhs.split(":")]
    mode = parts[0]
    if mode == "fail":
        if len(parts) > 2:
            raise ValueError(f"bad fault spec {item!r} (fail[:N])")
        count = int(parts[1]) if len(parts) == 2 else 1
        return point, _Armed(mode="fail", remaining=count)
    if mode == "delay":
        if len(parts) < 2 or len(parts) > 3:
            raise ValueError(f"bad fault spec {item!r} (delay:SECONDS[:N])")
        delay_s = float(parts[1])
        if delay_s < 0:
            raise ValueError(f"bad fault spec {item!r} (negative delay)")
        count = int(parts[2]) if len(parts) == 3 else -1
        return point, _Armed(mode="delay", remaining=count, delay_s=delay_s)
    raise ValueError(f"bad fault spec {item!r} (mode must be fail|delay)")


def parse_spec(spec: str) -> Dict[str, _Armed]:
    """Validate + parse a comma-separated spec string (see module doc)."""
    out: Dict[str, _Armed] = {}
    for item in (spec or "").split(","):
        item = item.strip()
        if not item:
            continue
        point, armed = _parse_one(item)
        out[point] = armed
    return out


class FaultRegistry:
    """Thread-safe map of armed injection points."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._points: Dict[str, _Armed] = {}
        self._env_loaded = False

    # -- arming ------------------------------------------------------------

    def arm(
        self,
        point: str,
        mode: str = "fail",
        count: Optional[int] = None,
        delay_s: float = 0.0,
    ) -> None:
        """Programmatic arming; ``count=None`` takes the mode's documented
        default — fail once, delay every time — matching the spec grammar
        (``p=fail`` vs ``p=delay:S``)."""
        if count is None:
            count = 1 if mode == "fail" else -1
        _, armed = _parse_one(
            f"{point}={mode}:{delay_s}:{count}"
            if mode == "delay"
            else f"{point}={mode}:{count}"
        )
        with self._mu:
            self._points[point] = armed

    def arm_spec(self, spec: str) -> None:
        parsed = parse_spec(spec)
        with self._mu:
            self._points.update(parsed)

    def disarm(self, point: str) -> None:
        with self._mu:
            self._points.pop(point, None)

    def reset(self) -> None:
        with self._mu:
            self._points.clear()

    def load_env(self, force: bool = False) -> None:
        """Arm from ``FMA_FAULTS`` once per process (idempotent: a second
        service constructed in the same process must not re-arm points the
        first already consumed). ``force`` re-reads regardless — the
        forked engine child uses it after applying its per-instance
        env_vars, because the latch is inherited from the launcher."""
        with self._mu:
            if self._env_loaded and not force:
                return
            self._env_loaded = True
        spec = os.environ.get(ENV_VAR, "")
        if spec:
            self.arm_spec(spec)

    # -- firing ------------------------------------------------------------

    def fire(self, point: str) -> None:
        """Act on ``point`` if armed: raise :class:`FaultError` (fail) or
        sleep (delay). No-op — one dict lookup under a lock — otherwise."""
        with self._mu:
            armed = self._points.get(point)
            if armed is None or armed.remaining == 0:
                return
            if armed.remaining > 0:
                armed.remaining -= 1
            armed.fired += 1
            if armed.mode == "fail":
                if armed.remaining == 0:
                    # consumed: drop so describe() shows only live points
                    self._points.pop(point, None)
                raise FaultError(point)
            delay_s = armed.delay_s
            if armed.remaining == 0:
                self._points.pop(point, None)
        time.sleep(delay_s)  # outside the lock: a delay must not serialize

    # -- observability -----------------------------------------------------

    def describe(self) -> Dict[str, Any]:
        with self._mu:
            return {
                "known_points": list(KNOWN_POINTS),
                "armed": {
                    p: {
                        "mode": a.mode,
                        "remaining": a.remaining,
                        "delay_s": a.delay_s,
                        "fired": a.fired,
                    }
                    for p, a in self._points.items()
                },
            }


#: the process-global registry every injection site fires into
FAULTS = FaultRegistry()


def fire(point: str) -> None:
    FAULTS.fire(point)


def arm(
    point: str,
    mode: str = "fail",
    count: Optional[int] = None,
    delay_s: float = 0.0,
) -> None:
    FAULTS.arm(point, mode=mode, count=count, delay_s=delay_s)


def arm_spec(spec: str) -> None:
    FAULTS.arm_spec(spec)


def disarm(point: str) -> None:
    FAULTS.disarm(point)


def reset() -> None:
    FAULTS.reset()


def load_env(force: bool = False) -> None:
    FAULTS.load_env(force=force)


def describe() -> Dict[str, Any]:
    return FAULTS.describe()
