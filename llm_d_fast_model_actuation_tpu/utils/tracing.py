"""End-to-end actuation tracing: propagated spans across the control plane.

The paper's headline claim is an actuation-latency *envelope* (sleep/wake in
~3 s, dual-pods actuation in seconds); the metrics catalog can say how long
one actuation took, but not *which hop* — SPI call, launcher RPC, child
spawn, D2H/H2D stream, rollback — ate the time. This module turns the
existing timing scaffolding into attributable timelines:

  * **Spans** — trace_id / span_id / parent, name, attrs, monotonic
    start/end — recorded into a bounded per-process ring buffer (no
    unbounded growth; old spans fall off the back).
  * **Propagation** — W3C ``traceparent`` headers threaded through the
    instrumented HTTP paths (controller `clients.py`, launcher
    `_engine_request`, the engine's admin handlers) and the
    ``FMA_TRACEPARENT`` env var into forked engine children — so one
    actuation (requester create → controller bind → launcher spawn/wake →
    engine swap commit) is a single coherent trace across processes.
  * **Export** — Chrome trace-event JSON (loads directly in Perfetto /
    chrome://tracing; each process's ring buffer exports with wall-clock
    anchored timestamps, so per-process exports concatenate into one
    timeline) and a human ``tree`` rendering. Served by the engine's
    ``GET /v1/traces`` and the controller observability port's
    ``/debug/traces``.

Overhead discipline: tracing is ON by default (a span is two monotonic
reads, one small object, and a bounded deque append), and ``FMA_TRACING=off``
(or :func:`disable`) turns every entry point into a shared no-op — hot
loops (the swap bucket loop in engine/sleep.py) hoist :func:`enabled` once
and skip span creation entirely, so the disabled path adds no per-chunk
allocations.

Spans are deliberately NOT OpenTelemetry objects: the container must not
grow a dependency, and the subset here (sync spans, explicit parents for
worker threads, context managers over the step-shaped control flow we
have) is what the actuation paths need. The wire format (traceparent) and
the export format (Chrome trace events) are the standard ones, so external
tooling plugs in unchanged.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

#: env toggles: FMA_TRACING=off|0|false disables at import; FMA_TRACE_BUFFER
#: overrides the ring capacity (spans retained per process).
ENV_VAR = "FMA_TRACING"
BUFFER_ENV_VAR = "FMA_TRACE_BUFFER"
#: the cross-fork propagation channel: the launcher stamps the current
#: traceparent here around the child fork; the engine service adopts it as
#: the parent of its startup span.
TRACEPARENT_ENV = "FMA_TRACEPARENT"

DEFAULT_BUFFER_SPANS = 4096

#: wall-clock anchor: spans carry monotonic times (immune to clock steps);
#: export maps them onto the epoch so per-process exports line up on one
#: Perfetto timeline.
_ANCHOR_WALL = time.time()
_ANCHOR_MONO = time.monotonic()


def _wall(mono_s: float) -> float:
    return _ANCHOR_WALL + (mono_s - _ANCHOR_MONO)


@dataclass
class SpanContext:
    """The propagatable identity of a span: what a child (local, HTTP, or
    forked-process) parents itself on."""

    trace_id: str
    span_id: str


@dataclass
class Span:
    """One finished (or in-flight) span. ``start_s``/``end_s`` are
    monotonic; attrs are small JSON-able scalars (bytes, bucket index,
    model name...)."""

    trace_id: str
    span_id: str
    parent_id: str
    name: str
    start_s: float
    end_s: float = 0.0
    attrs: Dict[str, Any] = field(default_factory=dict)
    pid: int = 0
    thread: str = ""

    @property
    def duration_s(self) -> float:
        return max(0.0, (self.end_s or self.start_s) - self.start_s)


class TraceBuffer:
    """Thread-safe bounded ring of finished spans (per process)."""

    def __init__(self, capacity: int = DEFAULT_BUFFER_SPANS) -> None:
        self._buf: deque = deque(maxlen=max(1, capacity))
        self._mu = threading.Lock()

    def add(self, span: Span) -> None:
        with self._mu:
            self._buf.append(span)

    def snapshot(self, trace_id: Optional[str] = None) -> List[Span]:
        with self._mu:
            spans = list(self._buf)
        if trace_id:
            spans = [s for s in spans if s.trace_id == trace_id]
        return spans

    def drain(self, trace_id: Optional[str] = None) -> List[Span]:
        """Atomic snapshot-and-remove: a span recorded between the two
        would otherwise be dropped unexported. With ``trace_id`` only
        that trace's spans are removed — other traces stay for their own
        later export."""
        with self._mu:
            spans = list(self._buf)
            self._buf.clear()
            if trace_id is None:
                return spans
            self._buf.extend(s for s in spans if s.trace_id != trace_id)
            return [s for s in spans if s.trace_id == trace_id]

    def clear(self) -> None:
        with self._mu:
            self._buf.clear()

    def __len__(self) -> int:
        with self._mu:
            return len(self._buf)


def _env_enabled() -> bool:
    return os.environ.get(ENV_VAR, "").lower() not in ("off", "0", "false")


def _env_capacity() -> int:
    try:
        return int(os.environ.get(BUFFER_ENV_VAR, "") or DEFAULT_BUFFER_SPANS)
    except ValueError:
        return DEFAULT_BUFFER_SPANS


_BUFFER = TraceBuffer(_env_capacity())
_enabled = _env_enabled()
_current: "contextvars.ContextVar[Optional[SpanContext]]" = (
    contextvars.ContextVar("fma_trace_ctx", default=None)
)


def enabled() -> bool:
    """Hot-loop guard: hoist this once per loop; when False, skip
    :func:`begin` entirely (no span objects, no attr dicts)."""
    return _enabled


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset_after_fork() -> None:
    """Forked-child hygiene (the launcher's process model): the fork
    duplicates the parent's ring buffers — drop the copies so the child's
    export is its own spans only, and re-read the env so per-instance
    env_vars (FMA_TRACING / FMA_TRACE_BUFFER) win over inherited state.
    Request sampling resets to 0 (off): the child re-applies its own
    ``--trace-requests`` during engine construction."""
    global _BUFFER, _enabled, _REQ_BUFFER, _req_frac
    _BUFFER = TraceBuffer(_env_capacity())
    _REQ_BUFFER = TraceBuffer(_req_env_capacity())
    _req_frac = 0.0
    _enabled = _env_enabled()
    _current.set(None)


# -- ids / W3C traceparent ----------------------------------------------------


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


def format_traceparent(ctx: SpanContext) -> str:
    """W3C trace-context header value: 00-<trace>-<span>-01."""
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


def parse_traceparent(value: Optional[str]) -> Optional[SpanContext]:
    """Parse a ``traceparent`` header / env value; None on anything
    malformed (a bad header must never break the request that carried
    it)."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, _flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(trace_id=trace_id, span_id=span_id)


def current_context() -> Optional[SpanContext]:
    return _current.get()


def current_traceparent() -> Optional[str]:
    ctx = _current.get()
    return format_traceparent(ctx) if ctx is not None else None


def context_from_headers(headers: Any) -> Optional[SpanContext]:
    """Adopt a remote parent from request headers (aiohttp CIMultiDict or
    any mapping with case-insensitive-enough .get)."""
    try:
        return parse_traceparent(
            headers.get("traceparent") or headers.get("Traceparent")
        )
    except Exception:  # noqa: BLE001 — odd header containers
        return None


def env_context() -> Optional[SpanContext]:
    """The cross-fork parent, if the spawning process stamped one."""
    return parse_traceparent(os.environ.get(TRACEPARENT_ENV, ""))


@contextlib.contextmanager
def use_context(ctx: Optional[SpanContext]) -> Iterator[None]:
    """Run a block with ``ctx`` as the current span context (no-op when
    ctx is None) — the executor-thread adoption helper: HTTP handlers
    parse the remote parent on the event loop and re-establish it inside
    the worker running the blocking admin call."""
    if ctx is None:
        yield
        return
    token = _current.set(ctx)
    try:
        yield
    finally:
        _current.reset(token)


# -- spans --------------------------------------------------------------------


class _NoopSpan:
    """The disabled-path singleton: every operation is a no-op, nothing
    allocates per call site."""

    __slots__ = ()
    trace_id = ""
    span_id = ""

    ended = True

    def set(self, **attrs: Any) -> "_NoopSpan":
        return self

    def end(self) -> None:
        return None

    def traceparent(self) -> Optional[str]:
        return None

    def context(self) -> Optional[SpanContext]:
        return None

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


class SpanHandle:
    """A live span. Usable as a context manager (``with span("x"): ...``)
    or with explicit ``end()`` for pipelined/overlapping lifetimes (the
    swap bucket loop issues several at once with ``activate=False``)."""

    __slots__ = ("_span", "_token", "_activated")

    def __init__(self, span: Span, token, activated: bool) -> None:
        self._span = span
        self._token = token
        self._activated = activated

    @property
    def trace_id(self) -> str:
        return self._span.trace_id

    @property
    def span_id(self) -> str:
        return self._span.span_id

    @property
    def ended(self) -> bool:
        return bool(self._span.end_s)

    def context(self) -> SpanContext:
        return SpanContext(self._span.trace_id, self._span.span_id)

    def traceparent(self) -> str:
        return format_traceparent(self.context())

    def set(self, **attrs: Any) -> "SpanHandle":
        self._span.attrs.update(attrs)
        return self

    def end(self) -> None:
        if self._span.end_s:
            return  # idempotent
        self._span.end_s = time.monotonic()
        if self._activated and self._token is not None:
            try:
                _current.reset(self._token)
            except ValueError:
                # ended on a different thread/context than it began on
                # (pipelined handles): the ContextVar was never theirs
                pass
            self._token = None
        _BUFFER.add(self._span)

    def __enter__(self) -> "SpanHandle":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is not None and "error" not in self._span.attrs:
            self._span.attrs["error"] = f"{type(exc).__name__}: {exc}"
        self.end()
        return False


def begin(
    name: str,
    parent: Optional[SpanContext] = None,
    activate: bool = True,
    **attrs: Any,
):
    """Start a span. ``parent`` overrides the ambient context (worker
    threads pass the captured parent explicitly — ContextVars do not cross
    thread starts); with ``activate=False`` the span does NOT become the
    current context, which is what overlapping (pipelined) spans in one
    thread need to avoid misparenting each other."""
    if not _enabled:
        return NOOP_SPAN
    ctx = parent if parent is not None else _current.get()
    span = Span(
        trace_id=ctx.trace_id if ctx else _new_trace_id(),
        span_id=_new_span_id(),
        parent_id=ctx.span_id if ctx else "",
        name=name,
        start_s=time.monotonic(),
        attrs=dict(attrs) if attrs else {},
        pid=os.getpid(),
        thread=threading.current_thread().name,
    )
    token = None
    if activate:
        token = _current.set(SpanContext(span.trace_id, span.span_id))
    return SpanHandle(span, token, activate)


def span(
    name: str, parent: Optional[SpanContext] = None, **attrs: Any
):
    """``with tracing.span("engine.swap", model=m): ...`` — begin +
    activate, ended (and attrs stamped with any exception) on exit."""
    return begin(name, parent=parent, activate=True, **attrs)


def snapshot(trace_id: Optional[str] = None) -> List[Span]:
    return _BUFFER.snapshot(trace_id=trace_id)


def clear() -> None:
    _BUFFER.clear()


def buffer_len() -> int:
    return len(_BUFFER)


# -- request-scoped tracing ---------------------------------------------------
#
# The ``request.*`` span family (docs/tracing.md): one trace per served
# request, spans recorded retrospectively at lifecycle edges (explicit
# start/end monotonic times — no open handles crossing threads, no
# per-decode-step span flood). Retained spans land in a DEDICATED ring,
# separate from the actuation ring above, so decode traffic can never
# evict swap forensics (and vice versa). Retention is head sampling
# (``--trace-requests <frac>``) plus tail-keep: SLO-violated, aborted,
# and migrated requests always keep their spans.

#: ring capacity override for the request-span ring (spans per process).
REQ_BUFFER_ENV_VAR = "FMA_REQ_TRACE_BUFFER"
DEFAULT_REQ_BUFFER_SPANS = 8192


def _req_env_capacity() -> int:
    try:
        return int(
            os.environ.get(REQ_BUFFER_ENV_VAR, "")
            or DEFAULT_REQ_BUFFER_SPANS
        )
    except ValueError:
        return DEFAULT_REQ_BUFFER_SPANS


_REQ_BUFFER = TraceBuffer(_req_env_capacity())
_req_frac = 0.0


def configure_request_sampling(frac: float) -> None:
    """Set the head-sampling fraction for request traces
    (``--trace-requests``). 0 — the default — keeps the serving hot path
    byte-inert: no RequestTrace objects are created and every hook
    reduces to one ``is None`` check."""
    global _req_frac
    try:
        _req_frac = min(1.0, max(0.0, float(frac)))
    except (TypeError, ValueError):
        _req_frac = 0.0


def request_sampling() -> float:
    return _req_frac


def sample_request() -> bool:
    """One head-sampling draw, decided at request creation. The draw is
    carried on the RequestTrace (``sampled``) so tail-keep can overrule
    a negative draw at completion — not the other way around."""
    return _enabled and _req_frac > 0.0 and random.random() < _req_frac


class RequestTrace:
    """Per-request span collector.

    Spans accumulate privately on the instance (appends are GIL-atomic;
    the engine's step discipline serializes real mutators anyway) and
    nothing touches any ring until :meth:`finish` decides retention:
    head-sampled requests keep their spans, everyone else's are dropped
    at completion unless tail-keep (SLO violation / abort / migration)
    overrules. The lifecycle root's span_id is allocated up front so
    child spans — including spans recorded by ANOTHER process after a
    migration, via :meth:`context` serialized into the parked bundle —
    parent on it before it is finished."""

    __slots__ = ("trace_id", "root_id", "parent_id", "sampled", "spans",
                 "_done")

    def __init__(
        self,
        sampled: bool = False,
        parent: Optional[SpanContext] = None,
    ) -> None:
        self.trace_id = parent.trace_id if parent else _new_trace_id()
        self.parent_id = parent.span_id if parent else ""
        self.root_id = _new_span_id()
        self.sampled = bool(sampled)
        self.spans: List[Span] = []
        self._done = False

    def context(self) -> SpanContext:
        """What a child recorded elsewhere (another thread, or another
        process across the migration wire) parents on: the lifecycle
        root."""
        return SpanContext(self.trace_id, self.root_id)

    def traceparent(self) -> str:
        return format_traceparent(self.context())

    def add(
        self,
        name: str,
        start_s: float,
        end_s: float,
        parent_id: Optional[str] = None,
        **attrs: Any,
    ) -> str:
        """Record one retrospective child span from explicit monotonic
        times; returns its span_id (for grandchildren)."""
        span = Span(
            trace_id=self.trace_id,
            span_id=_new_span_id(),
            parent_id=self.root_id if parent_id is None else parent_id,
            name=name,
            start_s=float(start_s),
            end_s=float(end_s),
            attrs=dict(attrs) if attrs else {},
            pid=os.getpid(),
            thread=threading.current_thread().name,
        )
        self.spans.append(span)
        return span.span_id

    def finish(
        self,
        start_s: float,
        end_s: float,
        keep: bool,
        name: str = "request.lifecycle",
        **attrs: Any,
    ) -> str:
        """Build the ``request.lifecycle`` root over [start_s, end_s] and,
        iff ``keep``, flush root + children to the request ring. Always
        returns the trace_id; idempotent (a double finish flushes
        nothing twice)."""
        if self._done:
            return self.trace_id
        self._done = True
        if keep:
            _REQ_BUFFER.add(
                Span(
                    trace_id=self.trace_id,
                    span_id=self.root_id,
                    parent_id=self.parent_id,
                    name=name,
                    start_s=float(start_s),
                    end_s=float(end_s),
                    attrs=dict(attrs) if attrs else {},
                    pid=os.getpid(),
                    thread=threading.current_thread().name,
                )
            )
            for s in self.spans:
                _REQ_BUFFER.add(s)
        self.spans = []
        return self.trace_id


def request_snapshot(trace_id: Optional[str] = None) -> List[Span]:
    return _REQ_BUFFER.snapshot(trace_id=trace_id)


def request_buffer_len() -> int:
    return len(_REQ_BUFFER)


def clear_requests() -> None:
    _REQ_BUFFER.clear()


# -- export -------------------------------------------------------------------


def _jsonable(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


def export_chrome(spans: List[Span]) -> Dict[str, Any]:
    """Chrome trace-event JSON (the JSON Array Format with complete "X"
    events) — loads directly in Perfetto and chrome://tracing. Timestamps
    are wall-anchored microseconds, so exports from several processes
    concatenate into one coherent timeline; args carry the span identity
    for cross-process tree reassembly."""
    events = []
    for s in spans:
        events.append(
            {
                "name": s.name,
                "cat": "fma",
                "ph": "X",
                "ts": round(_wall(s.start_s) * 1e6, 3),
                "dur": round(s.duration_s * 1e6, 3),
                "pid": s.pid,
                "tid": s.thread or "main",
                "args": {
                    "trace_id": s.trace_id,
                    "span_id": s.span_id,
                    "parent_id": s.parent_id,
                    **{k: _jsonable(v) for k, v in s.attrs.items()},
                },
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def spans_from_chrome(payload: Dict[str, Any]) -> List[Span]:
    """Inverse of :func:`export_chrome` (identity fields + timings): lets
    a caller merge another process's export (e.g. the engine child's
    ``GET /v1/traces``) with its own spans into one tree."""
    out: List[Span] = []
    for e in payload.get("traceEvents", []):
        args = dict(e.get("args") or {})
        trace_id = args.pop("trace_id", "")
        span_id = args.pop("span_id", "")
        parent_id = args.pop("parent_id", "")
        if not trace_id or not span_id:
            continue
        start = float(e.get("ts", 0.0)) / 1e6
        dur = float(e.get("dur", 0.0)) / 1e6
        # wall-anchored ts mapped back onto THIS process's monotonic axis,
        # so merged spans sort/nest consistently with local ones
        start_mono = _ANCHOR_MONO + (start - _ANCHOR_WALL)
        out.append(
            Span(
                trace_id=trace_id,
                span_id=span_id,
                parent_id=parent_id,
                name=str(e.get("name", "")),
                start_s=start_mono,
                end_s=start_mono + dur,
                attrs=args,
                pid=int(e.get("pid", 0) or 0),
                thread=str(e.get("tid", "")),
            )
        )
    return out


def build_tree(
    spans: List[Span],
) -> Tuple[List[Span], Dict[str, List[Span]]]:
    """(roots, children-by-span_id). A span whose parent is absent from
    the set (evicted from the ring, or recorded by a process we did not
    merge) is treated as a root rather than dropped."""
    by_id = {s.span_id: s for s in spans}
    children: Dict[str, List[Span]] = {}
    roots: List[Span] = []
    for s in spans:
        if s.parent_id and s.parent_id in by_id:
            children.setdefault(s.parent_id, []).append(s)
        else:
            roots.append(s)
    for v in children.values():
        v.sort(key=lambda s: s.start_s)
    roots.sort(key=lambda s: s.start_s)
    return roots, children


def render_tree(spans: List[Span]) -> str:
    """Human rendering: one indented tree per trace, durations in ms,
    attrs inline — the "why was THIS actuation slow" view."""
    lines: List[str] = []
    by_trace: Dict[str, List[Span]] = {}
    for s in spans:
        by_trace.setdefault(s.trace_id, []).append(s)
    for trace_id in sorted(by_trace):
        lines.append(f"trace {trace_id}")
        roots, children = build_tree(by_trace[trace_id])

        def walk(node: Span, depth: int) -> None:
            attrs = " ".join(
                f"{k}={_jsonable(v)}" for k, v in node.attrs.items()
            )
            lines.append(
                "  " * (depth + 1)
                + f"{node.name}  {node.duration_s * 1e3:.2f}ms"
                + (f"  [{attrs}]" if attrs else "")
            )
            for c in children.get(node.span_id, []):
                walk(c, depth + 1)

        for r in roots:
            walk(r, 0)
    return "\n".join(lines) + "\n"


def export_http(
    fmt: str = "chrome",
    trace_id: Optional[str] = None,
    clear: bool = False,
) -> Tuple[int, str, str]:
    """(status, body, content_type) — the shared body of the three export
    endpoints (engine ``/v1/traces``, launcher ``/v2/vllm/traces``,
    controller ``/debug/traces``), so format validation and the
    snapshot/clear semantics cannot drift between them. ``fmt`` is
    ``chrome`` (Perfetto-loadable JSON, the default) or ``tree`` (text);
    ``clear`` drains atomically with the snapshot, and composed with
    ``trace_id`` removes ONLY the exported trace — other traces' spans
    are never dropped unexported. Exports the union of the actuation
    ring and the request-span ring (a ``trace_id`` filter naturally
    scopes to whichever ring holds that trace)."""
    import json

    if fmt not in ("chrome", "tree"):
        return 400, "format must be chrome or tree\n", "text/plain"
    spans = (
        _BUFFER.drain(trace_id) if clear else _BUFFER.snapshot(trace_id)
    )
    spans += (
        _REQ_BUFFER.drain(trace_id)
        if clear
        else _REQ_BUFFER.snapshot(trace_id)
    )
    if fmt == "tree":
        return 200, render_tree(spans), "text/plain"
    return 200, json.dumps(export_chrome(spans)), "application/json"


def wrap_with_headers(headers: Any, fn):
    """Zero-arg callable running ``fn`` with the headers' ``traceparent``
    (if any) as the current context — the run_in_executor adoption
    pattern shared by the engine and launcher REST handlers (ContextVars
    don't follow executor dispatch on their own)."""
    ctx = context_from_headers(headers)

    def call():
        with use_context(ctx):
            return fn()

    return call


def run_traced(loop: Any, headers: Any, fn):
    """``loop.run_in_executor`` of a blocking call with the headers'
    remote ``traceparent`` adopted inside the worker thread — the one
    REST-handler dispatch pattern every traced server uses."""
    return loop.run_in_executor(None, wrap_with_headers(headers, fn))
