"""Raw host<->device transfer bandwidth measurement.

One implementation shared by bench.py and scripts/tpu_profile.py so the
`tunnel_*_gibps` numbers the two tools report are comparable. On the axon
development tunnel this measures the tunnel itself (the environment
ceiling for checkpoint load and release-cycle numbers); on directly
attached TPU hosts it measures PCIe.
"""

from __future__ import annotations

import time
from typing import Tuple


def measure_tunnel_bandwidth(mib: int = 256) -> Tuple[float, float]:
    """Returns (host_to_device_gibps, device_to_host_gibps) for one `mib`
    MiB float32 transfer each way. The probe buffers are freed before
    returning."""
    import jax
    import numpy as np

    x_host = np.ones((mib, 1024, 256), np.float32)  # mib MiB
    t0 = time.monotonic()
    x_dev = jax.block_until_ready(jax.device_put(x_host))
    h2d = (mib / 1024) / max(time.monotonic() - t0, 1e-9)
    t0 = time.monotonic()
    np.asarray(x_dev)
    d2h = (mib / 1024) / max(time.monotonic() - t0, 1e-9)
    x_dev.delete()
    del x_host
    return h2d, d2h
