"""Version-drift shims for the jax/jaxlib APIs this repo straddles.

The container images this runs on carry different jax point releases, and
two APIs have moved across them:

  * ``shard_map`` graduated from ``jax.experimental.shard_map`` to
    ``jax.shard_map``. Import it from here; both spellings resolve.
  * Pallas-TPU compiler params were renamed
    ``TPUCompilerParams`` -> ``CompilerParams``. ``tpu_compiler_params()``
    builds whichever this install ships.

Keep this module dependency-light: it is imported by ops/ and parallel/
alike, before any backend is initialized.
"""

from __future__ import annotations

import jax

try:  # jax >= 0.6 spelling
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # the long-lived experimental home
    from jax.experimental.shard_map import shard_map  # noqa: F401


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` (new) or ``pltpu.TPUCompilerParams`` (old),
    constructed with the given fields — the dataclass fields themselves
    (``dimension_semantics`` et al.) are stable across the rename."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    return cls(**kwargs)


def pallas_interpret_supported() -> bool:
    """Capability probe: can this jaxlib run a trivial Pallas kernel in
    interpreter mode on the current (CPU) backend? Some jax/jaxlib pairs
    in the wild cannot lower even interpret-mode pallas_call on CPU —
    tests gate on this instead of failing the sweep."""
    global _PALLAS_PROBE
    if _PALLAS_PROBE is None:
        try:
            import jax.numpy as jnp
            from jax.experimental import pallas as pl

            def _copy(x_ref, o_ref):
                o_ref[...] = x_ref[...]

            x = jnp.zeros((8, 128), jnp.float32)
            out = pl.pallas_call(
                _copy,
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                interpret=True,
            )(x)
            _PALLAS_PROBE = bool(out.shape == x.shape)
        except Exception:  # noqa: BLE001 — any failure means "can't"
            _PALLAS_PROBE = False
    return _PALLAS_PROBE


_PALLAS_PROBE: "bool | None" = None
