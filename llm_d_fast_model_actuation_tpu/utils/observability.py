"""One observability endpoint: prometheus metrics + python debug handlers.

The reference serves prometheus and Go pprof from one mux
(pkg/observability/prom-and-debug.go:34-79). The python-native analogue:

  GET /metrics       — prometheus exposition (default registry)
  GET /debug/stacks  — current traceback of every thread (the goroutine-dump
                       equivalent; what you want from a wedged controller)
  GET /debug/vars    — process vitals: rss, fds, gc counts, thread count,
                       process uptime
  GET /debug/traces  — this process's actuation-span ring buffer
                       (utils/tracing.py): Chrome trace-event JSON
                       (Perfetto-loadable) or ?format=tree

Runs on a daemon thread with the stdlib ThreadingHTTPServer — zero extra
dependencies, safe to import before an event loop exists.
"""

from __future__ import annotations

import gc
import json
import os
import sys
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

#: import-time anchor: the fallback for uptime_s when /proc is unavailable
#: (this module is imported early in every process that serves it)
_IMPORT_MONO = time.monotonic()


def _uptime_s() -> float:
    """Seconds since the PROCESS started (not since this module imported),
    via /proc where available — stuck-thread triage wants "has this
    controller been up 30 s or 30 days" without diffing /debug/stacks."""
    try:
        with open("/proc/self/stat") as f:
            fields = f.read().rsplit(")", 1)[1].split()
        start_ticks = float(fields[19])  # starttime: field 22 overall
        with open("/proc/uptime") as f:
            sys_uptime = float(f.read().split()[0])
        return max(0.0, sys_uptime - start_ticks / os.sysconf("SC_CLK_TCK"))
    except (OSError, IndexError, ValueError):
        return time.monotonic() - _IMPORT_MONO


def _dump_stacks() -> str:
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
    return "\n".join(out) + "\n"


def _vars() -> dict:
    info = {
        "pid": os.getpid(),
        "uptime_s": round(_uptime_s(), 3),
        "threads": threading.active_count(),
        "gc_counts": gc.get_count(),
        "gc_objects": len(gc.get_objects()),
    }
    try:
        with open(f"/proc/{os.getpid()}/status") as f:
            for line in f:
                if line.startswith(("VmRSS", "VmHWM", "Threads", "FDSize")):
                    k, v = line.split(":", 1)
                    info["proc_" + k.lower()] = v.strip()
    except OSError:
        pass
    return info


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # quiet
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 (stdlib API)
        path, _, query = self.path.partition("?")
        if path == "/metrics":
            from prometheus_client import generate_latest

            self._send(200, generate_latest(), "text/plain; version=0.0.4")
        elif path == "/debug/stacks":
            self._send(200, _dump_stacks().encode(), "text/plain")
        elif path == "/debug/vars":
            self._send(
                200, json.dumps(_vars(), default=str).encode(), "application/json"
            )
        elif path == "/debug/traces":
            from urllib.parse import parse_qs

            from . import tracing

            q = parse_qs(query)
            status, body, ctype = tracing.export_http(
                (q.get("format") or ["chrome"])[0],
                trace_id=(q.get("trace_id") or [None])[0],
                clear=(q.get("clear") or [""])[0] in ("1", "true"),
            )
            self._send(status, body.encode(), ctype)
        else:
            self._send(404, b"not found\n", "text/plain")


def serve_observability(
    port: int, host: str = "0.0.0.0"
) -> ThreadingHTTPServer:
    """Start the metrics+debug server on a daemon thread; returns the server
    (tests call .shutdown())."""
    server = ThreadingHTTPServer((host, port), _Handler)
    t = threading.Thread(
        target=server.serve_forever, daemon=True, name="observability"
    )
    t.start()
    return server
