"""One observability endpoint: prometheus metrics + python debug handlers.

The reference serves prometheus and Go pprof from one mux
(pkg/observability/prom-and-debug.go:34-79). The python-native analogue:

  GET /metrics       — prometheus exposition (default registry)
  GET /debug/stacks  — current traceback of every thread (the goroutine-dump
                       equivalent; what you want from a wedged controller)
  GET /debug/vars    — process vitals: rss, fds, gc counts, thread count

Runs on a daemon thread with the stdlib ThreadingHTTPServer — zero extra
dependencies, safe to import before an event loop exists.
"""

from __future__ import annotations

import gc
import json
import os
import sys
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


def _dump_stacks() -> str:
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
    return "\n".join(out) + "\n"


def _vars() -> dict:
    info = {
        "pid": os.getpid(),
        "threads": threading.active_count(),
        "gc_counts": gc.get_count(),
        "gc_objects": len(gc.get_objects()),
    }
    try:
        with open(f"/proc/{os.getpid()}/status") as f:
            for line in f:
                if line.startswith(("VmRSS", "VmHWM", "Threads", "FDSize")):
                    k, v = line.split(":", 1)
                    info["proc_" + k.lower()] = v.strip()
    except OSError:
        pass
    return info


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # quiet
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 (stdlib API)
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            from prometheus_client import generate_latest

            self._send(200, generate_latest(), "text/plain; version=0.0.4")
        elif path == "/debug/stacks":
            self._send(200, _dump_stacks().encode(), "text/plain")
        elif path == "/debug/vars":
            self._send(
                200, json.dumps(_vars(), default=str).encode(), "application/json"
            )
        else:
            self._send(404, b"not found\n", "text/plain")


def serve_observability(
    port: int, host: str = "0.0.0.0"
) -> ThreadingHTTPServer:
    """Start the metrics+debug server on a daemon thread; returns the server
    (tests call .shutdown())."""
    server = ThreadingHTTPServer((host, port), _Handler)
    t = threading.Thread(
        target=server.serve_forever, daemon=True, name="observability"
    )
    t.start()
    return server
