"""Prometheus metrics catalog — parity with the reference's docs/metrics.md.

Reference metric names are kept verbatim (fma_*) so dashboards/alerts port
unchanged. Registered on the default registry; `serve_metrics` exposes them.
"""

from __future__ import annotations

from prometheus_client import Counter, Gauge, Histogram

# Actuation latency with path classification (controller.go:265-271):
# hot  = provider existed with the instance awake,
# warm = instance existed asleep (wake path),
# cold = launcher or instance had to be created.
ACTUATION_SECONDS = Histogram(
    "fma_actuation_seconds",
    "Time from requester creation to first readiness relay",
    ["path", "instancesDeleted", "isc_name"],
    buckets=(0, 1, 3, 5, 7.5, 10, 15, 30, 60, 120, 240, 480, 960, 1920),
)

LAUNCHER_CREATE_SECONDS = Histogram(
    "fma_launcher_create_seconds",
    "Latency of creating a launcher Pod",
    ["lcfg_name"],
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 2, 5),
)

HTTP_LATENCY = Histogram(
    "fma_http_latency_seconds",
    "Latency of controller-originated HTTP calls",
    ["purpose", "method"],
)

DUALITY = Gauge(
    "fma_duality",
    "1 while a requester/provider pair is bound (join with per-chip metrics)",
    ["isc_name", "chip", "node"],
)

REQUESTER_COUNT = Gauge(
    "fma_requester_count",
    "Number of server-requesting Pods per InferenceServerConfig",
    ["isc_name"],
)

ISC_COUNT = Gauge(
    "fma_isc_count",
    "Number of InferenceServerConfigs per LauncherConfig",
    ["launcher_config_name"],
)

LAUNCHER_POD_COUNT = Gauge(
    "fma_launcher_pod_count",
    "Launcher Pods by lifecycle phase",
    ["lcfg_name", "phase"],
)

INNER_QUEUE_DEPTH = Gauge(
    "fma_dpc_innerqueue_depth",
    "Depth of the per-node serialized work queue",
    ["node"],
)

INNER_QUEUE_ADDS = Counter(
    "fma_dpc_innerqueue_adds_total",
    "Items added to the per-node work queue",
    ["node"],
)

INNER_QUEUE_RETRIES = Counter(
    "fma_dpc_innerqueue_retries_total",
    "Per-node queue item retries",
    ["node"],
)

WORK_DURATION = Histogram(
    "fma_dpc_innerqueue_work_duration_seconds",
    "Per-item processing time in the per-node queue",
    ["node"],
)

QUEUE_DURATION = Histogram(
    "fma_dpc_innerqueue_queue_duration_seconds",
    "Time an item waits in the per-node queue before processing",
    ["node"],
    buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 30),
)


def serve_metrics(port: int = 8002) -> None:
    """Prometheus + debug on one port (the reference serves both from one
    mux, pkg/observability/prom-and-debug.go:34-79); see utils/observability
    for the /debug endpoints."""
    from ..utils.observability import serve_observability

    serve_observability(port)
