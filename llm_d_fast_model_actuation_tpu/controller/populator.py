"""Launcher-populator: proactive launcher Pod population per policy.

Re-design of `pkg/controller/launcher-populator/` (2,960 LoC Go): a
two-stage asyncio controller:

  * a single **digest worker** — the sole writer of the digested policy
    (node x LauncherConfig -> desired count), fed by LPP/LC/Node events;
    user errors (missing/invalid LC) digest to HANDS_OFF and are reported on
    the LPP/LC `.status.errors` (this controller is their sole status writer);
  * **key workers** — per-(node, LC) reconciliation: categorize launchers
    bound / live-unbound-current / stale (template-hash drift) / deleting;
    delete stale and excess unbound (never bound ones) with UID+RV
    preconditions; create the difference from the node-specialized template.

Anti-stale-cache **pending expectations** (pending_expectations.go:31-157):
created/deleted pod UIDs are remembered until observed, with a timeout
fallback to a fresh list. Phase metrics (bound/unbound/stuck_scheduling/
stuck_starting/stale) mirror metrics.go:36-304, with event-driven
re-reconcile scheduled at the next phase-flip instant.
"""

from __future__ import annotations

import asyncio
import secrets
import json
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Dict, List, Optional, Set, Tuple

from ..api import constants as C
from ..api.types import (
    EnhancedNodeSelector,
    LauncherConfig,
    LauncherPopulationPolicy,
)
from ..utils.syncbarrier import KnowsProcessedSync
from ..utils.hashing import sha256_hex, template_hash
from . import metrics as M
from .store import Conflict, InMemoryStore, NotFound

logger = logging.getLogger(__name__)

HANDS_OFF = -1  # user error: leave this (node, lc) cell alone


# --------------------------------------------------------------------------
# pending expectations
# --------------------------------------------------------------------------

SATISFIED = "Satisfied"
WAITING = "Waiting"
TIMED_OUT = "TimedOut"


class PendingExpectations:
    """Track pod UIDs we created/deleted until the cache reflects them."""

    def __init__(self, timeout_s: float = 5.0) -> None:
        self.timeout_s = timeout_s
        self._created: Dict[str, float] = {}
        self._deleted: Dict[str, float] = {}

    def expect_creation(self, uid: str) -> None:
        self._created[uid] = time.monotonic()

    def expect_deletion(self, uid: str) -> None:
        self._deleted[uid] = time.monotonic()

    def check(self, present_uids: Set[str]) -> str:
        now = time.monotonic()
        for uid in list(self._created):
            if uid in present_uids:
                del self._created[uid]
        for uid in list(self._deleted):
            if uid not in present_uids:
                del self._deleted[uid]
        pending = list(self._created.values()) + list(self._deleted.values())
        if not pending:
            return SATISFIED
        if any(now - t > self.timeout_s for t in pending):
            return TIMED_OUT
        return WAITING

    def reset(self) -> None:
        self._created.clear()
        self._deleted.clear()


# --------------------------------------------------------------------------
# digested policy
# --------------------------------------------------------------------------


@dataclass
class LcDigest:
    obj: Optional[LauncherConfig] = None
    template_error: str = ""
    template_hash: str = ""


@dataclass
class DigestEntry:
    desired: int = 0
    lpps: Set[str] = field(default_factory=set)


@dataclass
class LppDigest:
    """Cached parse of one LPP + the node names its selector matches —
    the state that makes per-event incremental row rebuilds possible
    (digest-updater.go keeps the same association)."""

    lpp: LauncherPopulationPolicy
    matched: Set[str] = field(default_factory=set)


class DigestedPolicy:
    """node -> lc -> DigestEntry; plus per-LC digests. Single writer (the
    digest worker); key workers read value snapshots."""

    def __init__(self) -> None:
        self.digest: Dict[str, Dict[str, DigestEntry]] = {}
        self.lcs: Dict[str, LcDigest] = {}

    def snapshot_for_key(self, node: str, lc: str) -> Tuple[int, Optional[LcDigest]]:
        entry = (self.digest.get(node) or {}).get(lc)
        return (entry.desired if entry else 0), self.lcs.get(lc)

    def keys(self) -> List[Tuple[str, str]]:
        return [(n, lc) for n, row in self.digest.items() for lc in row]


def node_matches(node: Dict[str, Any], sel: EnhancedNodeSelector) -> bool:
    labels = (node.get("metadata") or {}).get("labels") or {}
    if not all(labels.get(k) == v for k, v in sel.match_labels.items()):
        return False
    alloc = (node.get("status") or {}).get("allocatable") or {}
    for res, rng in sel.allocatable_resources.items():
        if res not in alloc:
            return False
        try:
            if not rng.matches(alloc[res]):
                return False
        except ValueError:
            return False
    return True


# --------------------------------------------------------------------------
# the controller
# --------------------------------------------------------------------------


@dataclass
class PopulatorConfig:
    namespace: str = ""
    expectation_timeout_s: float = 5.0
    stuck_scheduling_threshold_s: float = 120.0
    stuck_starting_threshold_s: float = 450.0
    #: deployment glue: make a created launcher Pod actually run (tests)
    launcher_runtime: Optional[Callable[[Dict[str, Any]], Awaitable[None]]] = None


class Populator:
    def __init__(
        self, store: InMemoryStore, cfg: Optional[PopulatorConfig] = None
    ) -> None:
        self.store = store
        self.cfg = cfg or PopulatorConfig()
        self.policy = DigestedPolicy()
        self._lpp_digests: Dict[str, LppDigest] = {}
        self._digest_queue: asyncio.Queue = asyncio.Queue()
        self._key_queue: asyncio.Queue = asyncio.Queue()
        self._expectations: Dict[Tuple[str, str], PendingExpectations] = {}
        self._phase_timers: Dict[Tuple[str, str], asyncio.TimerHandle] = {}
        self._unsub: Optional[Callable[[], None]] = None
        self._tasks: List[asyncio.Task] = []
        self._stopping = False
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._inflight = 0
        self._active_keys: Set[Tuple[str, str]] = set()
        #: fires when every initially-present LC/LPP/Node had a digest pass
        self.initial_sync = KnowsProcessedSync()

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._unsub = self.store.subscribe(self._on_event)
        self._tasks.append(self._loop.create_task(self._digest_worker()))
        for _ in range(4):
            self._tasks.append(self._loop.create_task(self._key_worker()))
        # initial digest of existing objects
        for obj in self.store.all_objects():
            self._route(obj)
        self.initial_sync.arm()

    async def stop(self) -> None:
        self._stopping = True
        if self._unsub:
            self._unsub()
        for timer in self._phase_timers.values():
            timer.cancel()
        self._phase_timers.clear()
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass

    async def quiesce(self, timeout: float = 20.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if (
                self._inflight == 0
                and self._digest_queue.empty()
                and self._key_queue.empty()
            ):
                await asyncio.sleep(0.05)
                if (
                    self._inflight == 0
                    and self._digest_queue.empty()
                    and self._key_queue.empty()
                ):
                    return
            await asyncio.sleep(0.02)
        raise TimeoutError("populator did not quiesce")

    # -- event routing -------------------------------------------------------

    def _on_event(self, event: str, obj: Dict[str, Any]) -> None:
        if self._loop is None:
            return
        self._loop.call_soon_threadsafe(self._route, obj)

    def _route(self, obj: Dict[str, Any]) -> None:
        kind = obj.get("kind")
        name = (obj.get("metadata") or {}).get("name", "")
        if kind in (LauncherPopulationPolicy.KIND, LauncherConfig.KIND, "Node"):
            self.initial_sync.note_pending((kind, name))
            self._digest_queue.put_nowait((kind, name))
        elif kind == "Pod":
            lab = (obj.get("metadata") or {}).get("labels") or {}
            if lab.get(C.COMPONENT_LABEL) == C.LAUNCHER_COMPONENT:
                node = lab.get(C.NODE_NAME_LABEL) or (obj.get("spec") or {}).get(
                    "nodeName", ""
                )
                lc = lab.get(C.LAUNCHER_CONFIG_NAME_LABEL, "")
                if lc:
                    self._key_queue.put_nowait((node, lc))

    # -- digest stage --------------------------------------------------------

    async def _digest_worker(self) -> None:
        while not self._stopping:
            kind, name = await self._digest_queue.get()
            self._inflight += 1
            try:
                # digests do blocking HTTP status writes + O(nodes x LPPs)
                # recompute: keep the event loop free
                if kind == LauncherConfig.KIND:
                    await asyncio.to_thread(self._digest_lc, name)
                elif kind == LauncherPopulationPolicy.KIND:
                    await asyncio.to_thread(self._digest_lpp, name)
                else:  # Node
                    await asyncio.to_thread(self._digest_node, name)
            except Exception:
                logger.exception("digest of %s %s failed", kind, name)
            finally:
                self.initial_sync.note_processed((kind, name))
                self._inflight -= 1
                self._digest_queue.task_done()

    # Incremental digesting (the reference's digest-updater.go:42-287
    # design): each event rebuilds only the (node, lc) rows it can affect —
    # an LC touches the rows that reference it, an LPP touches its old+new
    # matched node sets, a Node touches its own row. The full recompute
    # survives only as the crash-consistency fallback.

    def _digest_lc(self, name: str) -> None:
        obj = self.store.try_get(LauncherConfig.KIND, self.cfg.namespace, name)
        if obj is None:
            self.policy.lcs.pop(name, None)
        else:
            self._digest_lc_obj(name, obj)
            err = self.policy.lcs[name].template_error
            self._write_status(LauncherConfig.KIND, name, [err] if err else [], obj)
        # only rows that reference this LC change (its desired/HANDS_OFF)
        affected = {
            node for node, row in self.policy.digest.items() if name in row
        }
        # plus rows of LPPs that reference it but had nothing digested yet
        for lname, ld in self._lpp_digests.items():
            if any(
                cfl.launcher_config_name == name
                for cfl in ld.lpp.spec.count_for_launcher
            ):
                affected |= ld.matched
                self._validate_lpp_status(lname)
        self._rebuild_rows(affected)
        # the LC itself changed (template hash / validity): its keys must
        # re-reconcile even when the digest cell value is unchanged —
        # template drift replaces stale unbound launchers
        self._enqueue_keys({(node, name) for node in affected})

    def _digest_lpp(self, name: str) -> None:
        obj = self.store.try_get(
            LauncherPopulationPolicy.KIND, self.cfg.namespace, name
        )
        old = self._lpp_digests.pop(name, None)
        affected: Set[str] = set(old.matched) if old else set()
        if obj is not None:
            lpp = LauncherPopulationPolicy.from_dict(obj)
            matched = {
                n["metadata"]["name"]
                for n in self.store.list("Node")
                if node_matches(n, lpp.spec.enhanced_node_selector)
            }
            self._lpp_digests[name] = LppDigest(lpp=lpp, matched=matched)
            affected |= matched
        self._rebuild_rows(affected)
        self._validate_lpp_status(name)

    def _validate_lpp_status(self, name: str) -> None:
        obj = self.store.try_get(
            LauncherPopulationPolicy.KIND, self.cfg.namespace, name
        )
        if obj is not None:
            lpp = LauncherPopulationPolicy.from_dict(obj)
            errors = []
            for cfl in lpp.spec.count_for_launcher:
                lcd = self.policy.lcs.get(cfl.launcher_config_name)
                if lcd is None or lcd.obj is None:
                    errors.append(
                        f"LauncherConfig {cfl.launcher_config_name} not found"
                    )
                elif lcd.template_error:
                    errors.append(
                        f"LauncherConfig {cfl.launcher_config_name}: {lcd.template_error}"
                    )
            self._write_status(LauncherPopulationPolicy.KIND, name, errors, obj)

    def _digest_node(self, name: str) -> None:
        obj = self.store.try_get("Node", "", name)
        for ld in self._lpp_digests.values():
            if obj is not None and node_matches(
                obj, ld.lpp.spec.enhanced_node_selector
            ):
                ld.matched.add(name)
            else:
                ld.matched.discard(name)
        self._rebuild_rows({name})

    def _rebuild_rows(self, nodes: Set[str]) -> None:
        """Recompute the digest rows for exactly `nodes` from the cached LPP
        digests, then enqueue every (node, lc) key whose cell appeared,
        changed, or vanished."""
        changed: Set[Tuple[str, str]] = set()
        for node in nodes:
            row: Dict[str, DigestEntry] = {}
            for lname, ld in self._lpp_digests.items():
                if node not in ld.matched:
                    continue
                for cfl in ld.lpp.spec.count_for_launcher:
                    entry = row.setdefault(cfl.launcher_config_name, DigestEntry())
                    entry.lpps.add(lname)
                    lcd = self.policy.lcs.get(cfl.launcher_config_name)
                    if lcd is None or lcd.obj is None or lcd.template_error:
                        entry.desired = HANDS_OFF
                    elif entry.desired != HANDS_OFF:
                        # all LPPs jointly define max(count)
                        entry.desired = max(entry.desired, cfl.launcher_count)
            old_row = self.policy.digest.get(node) or {}
            for lc in set(old_row) | set(row):
                a, b = old_row.get(lc), row.get(lc)
                if a is None or b is None or a.desired != b.desired or a.lpps != b.lpps:
                    changed.add((node, lc))
            if row:
                self.policy.digest[node] = row
            else:
                self.policy.digest.pop(node, None)
        self._enqueue_keys(changed)

    def _enqueue_keys(self, keys: Set[Tuple[str, str]]) -> None:
        # digests run off-loop (to_thread): hop through call_soon_threadsafe
        # when not on the loop
        try:
            on_loop = asyncio.get_running_loop() is self._loop
        except RuntimeError:
            on_loop = False
        for key in keys:
            if on_loop or self._loop is None:
                self._key_queue.put_nowait(key)
            else:
                self._loop.call_soon_threadsafe(self._key_queue.put_nowait, key)

    def _digest_lc_obj(self, name: str, obj: Dict[str, Any]) -> None:
        lc = LauncherConfig.from_dict(obj)
        err, thash = "", ""
        try:
            tpl, _ = build_launcher_template(lc)
            thash = template_hash(tpl)
        except Exception as e:
            err = f"invalid pod template: {e}"
        self.policy.lcs[name] = LcDigest(obj=lc, template_error=err, template_hash=thash)

    def _write_status(
        self, kind: str, name: str, errors: List[str], current: Dict[str, Any]
    ) -> None:
        gen = int((current.get("metadata") or {}).get("generation", 1))

        def apply(obj: Dict[str, Any]) -> Optional[Dict[str, Any]]:
            status = obj.setdefault("status", {})
            want = {"observedGeneration": gen}
            if errors:
                want["errors"] = errors
            if status == want:
                return None
            obj["status"] = want
            return obj

        try:
            self.store.mutate(kind, self.cfg.namespace, name, apply)
        except NotFound:
            pass

    # -- key stage -----------------------------------------------------------

    async def _key_worker(self) -> None:
        while not self._stopping:
            node, lc = await self._key_queue.get()
            key = (node, lc)
            # Per-key serialization: two workers reconciling the same
            # (node, lc) would both count the same world and double-create
            # across the awaits inside _reconcile_key. Defer to the holder
            # and run again once it is done.
            if key in self._active_keys:
                self._requeue_later(node, lc, 0.05)
                self._key_queue.task_done()
                continue
            self._active_keys.add(key)
            self._inflight += 1
            try:
                await self._reconcile_key(node, lc)
            except Exception:
                logger.exception("reconcile (%s, %s) failed", node, lc)
            finally:
                self._active_keys.discard(key)
                self._inflight -= 1
                self._key_queue.task_done()

    def _list_launchers(self, node: str, lc: str) -> List[Dict[str, Any]]:
        return self.store.list(
            "Pod",
            self.cfg.namespace,
            selector={
                C.COMPONENT_LABEL: C.LAUNCHER_COMPONENT,
                C.LAUNCHER_CONFIG_NAME_LABEL: lc,
            },
            predicate=lambda p: (p.get("spec") or {}).get("nodeName") == node,
        )

    async def _reconcile_key(self, node: str, lc_name: str) -> None:
        desired, lcd = self.policy.snapshot_for_key(node, lc_name)
        pods = self._list_launchers(node, lc_name)
        self._record_phases(node, lc_name, pods, lcd)

        if desired == HANDS_OFF:
            return  # user error: leave the world alone

        exp = self._expectations.setdefault(
            (node, lc_name), PendingExpectations(self.cfg.expectation_timeout_s)
        )
        state = exp.check({p["metadata"]["uid"] for p in pods})
        if state == WAITING:
            self._requeue_later(node, lc_name, 0.1)
            return
        if state == TIMED_OUT:
            exp.reset()
            pods = self._list_launchers(node, lc_name)  # fresh list

        bound: List[Dict[str, Any]] = []
        live_unbound: List[Dict[str, Any]] = []
        stale: List[Dict[str, Any]] = []
        deleting = 0
        for p in pods:
            m = p["metadata"]
            if m.get("deletionTimestamp") is not None:
                deleting += 1
                continue
            if C.REQUESTER_ANNOTATION in (m.get("annotations") or {}):
                bound.append(p)
            elif (
                lcd is not None
                and (m.get("annotations") or {}).get(C.LAUNCHER_TEMPLATE_HASH_ANNOTATION)
                == lcd.template_hash
            ):
                live_unbound.append(p)
            else:
                stale.append(p)

        # delete stale unbound and excess unbound (never bound ones)
        to_delete = list(stale)
        excess = len(live_unbound) - desired
        if excess > 0:
            to_delete.extend(live_unbound[:excess])
        for p in to_delete:
            m = p["metadata"]
            try:
                await asyncio.to_thread(
                    self.store.delete,
                    "Pod",
                    self.cfg.namespace,
                    m["name"],
                    expect_uid=m["uid"],
                    expect_rv=m["resourceVersion"],
                )
                exp.expect_deletion(m["uid"])
            except (NotFound, Conflict):
                pass
        if to_delete or deleting:
            self._requeue_later(node, lc_name, 0.1)  # requeue before creating
            return

        diff = desired - len(live_unbound)
        if diff > 0 and lcd is not None and lcd.obj is not None:
            for i in range(diff):
                pod = specialize_to_node(lcd.obj, node, lcd.template_hash)
                pod["metadata"]["namespace"] = self.cfg.namespace
                pod["metadata"]["name"] = (
                    f"{lc_name}-{node}-p{secrets.token_hex(4)}"
                )
                created = await asyncio.to_thread(self.store.create, pod)
                exp.expect_creation(created["metadata"]["uid"])
                if self.cfg.launcher_runtime is not None:
                    await self.cfg.launcher_runtime(created)
            logger.info("created %d launcher(s) for (%s, %s)", diff, node, lc_name)

    def _requeue_later(self, node: str, lc: str, delay: float) -> None:
        assert self._loop is not None
        self._inflight += 1

        def requeue() -> None:
            self._inflight -= 1
            if not self._stopping:
                self._key_queue.put_nowait((node, lc))

        self._loop.call_later(delay, requeue)

    # -- phase metrics -------------------------------------------------------

    def _phase_of(self, pod: Dict[str, Any], lcd: Optional[LcDigest]) -> str:
        m = pod["metadata"]
        if C.REQUESTER_ANNOTATION in (m.get("annotations") or {}):
            return "bound"
        if (
            lcd is None
            or (m.get("annotations") or {}).get(C.LAUNCHER_TEMPLATE_HASH_ANNOTATION)
            != lcd.template_hash
        ):
            return "stale"
        created = m.get("creationTimestamp") or time.time()
        age = time.time() - created
        st = pod.get("status") or {}
        scheduled = bool((pod.get("spec") or {}).get("nodeName"))
        ready = any(
            c.get("type") == "Ready" and c.get("status") == "True"
            for c in st.get("conditions", [])
        )
        if not scheduled and age > self.cfg.stuck_scheduling_threshold_s:
            return "stuck_scheduling"
        if scheduled and not ready and age > self.cfg.stuck_starting_threshold_s:
            return "stuck_starting"
        return "unbound"

    def _record_phases(
        self,
        node: str,
        lc_name: str,
        pods: List[Dict[str, Any]],
        lcd: Optional[LcDigest],
    ) -> None:
        counts: Dict[str, int] = {
            "bound": 0,
            "unbound": 0,
            "stuck_scheduling": 0,
            "stuck_starting": 0,
            "stale": 0,
        }
        next_flip: Optional[float] = None
        now = time.time()
        for p in pods:
            phase = self._phase_of(p, lcd)
            counts[phase] += 1
            # when will this pod's phase flip to stuck_*? schedule a
            # re-reconcile exactly then (metrics.go:297-304 — no sweeps)
            if phase == "unbound":
                created = p["metadata"].get("creationTimestamp") or now
                age = now - created
                scheduled = bool((p.get("spec") or {}).get("nodeName"))
                threshold = (
                    self.cfg.stuck_starting_threshold_s
                    if scheduled
                    else self.cfg.stuck_scheduling_threshold_s
                )
                remaining = threshold - age
                if remaining > 0 and (next_flip is None or remaining < next_flip):
                    next_flip = remaining
        for phase, count in counts.items():
            M.LAUNCHER_POD_COUNT.labels(lcfg_name=lc_name, phase=phase).set(count)
        if next_flip is not None:
            self._schedule_phase_recheck(node, lc_name, next_flip + 0.05)

    def _schedule_phase_recheck(self, node: str, lc: str, delay: float) -> None:
        """Timer for the next stuck_* phase flip. Unlike _requeue_later this
        does not count as in-flight work (it can be minutes away) and is
        deduplicated per key, keeping the earliest deadline."""
        assert self._loop is not None
        key = (node, lc)
        existing = self._phase_timers.get(key)
        if existing is not None:
            if existing.when() - self._loop.time() <= delay:
                return
            existing.cancel()

        def fire() -> None:
            self._phase_timers.pop(key, None)
            if not self._stopping:
                self._key_queue.put_nowait(key)

        self._phase_timers[key] = self._loop.call_later(delay, fire)


# --------------------------------------------------------------------------
# launcher template building (shared with the dual-pods controller)
# --------------------------------------------------------------------------


def build_launcher_template(lc: LauncherConfig) -> Tuple[Dict[str, Any], str]:
    """Node-independent launcher template (pod-helper.go:205-300): LC pod
    template + forced identity labels + launcher-port probes + the notifier
    sidecar env; returns (template, hash)."""
    spec = json.loads(json.dumps(lc.spec.pod_template.spec))
    if not spec.get("containers"):
        raise ValueError("pod template has no containers")
    tpl = {
        "metadata": {
            "labels": {
                **lc.spec.pod_template.labels,
                C.COMPONENT_LABEL: C.LAUNCHER_COMPONENT,
                C.LAUNCHER_CONFIG_NAME_LABEL: lc.metadata.name,
                C.SLEEPING_LABEL: "true",
            },
            "annotations": dict(lc.spec.pod_template.annotations),
        },
        "spec": spec,
    }
    return tpl, template_hash(tpl)


def specialize_to_node(
    lc: LauncherConfig, node: str, ti_hash: str
) -> Dict[str, Any]:
    """Template -> concrete Pod for a node (pod-helper.go:303-322)."""
    tpl, _ = build_launcher_template(lc)
    pod = json.loads(json.dumps(tpl))
    pod["kind"] = "Pod"
    pod["spec"]["nodeName"] = node
    pod["metadata"]["labels"][C.NODE_NAME_LABEL] = node
    pod["metadata"]["annotations"][C.LAUNCHER_TEMPLATE_HASH_ANNOTATION] = ti_hash
    pod["metadata"]["annotations"][C.LAUNCHER_CONFIG_HASH_ANNOTATION] = sha256_hex(
        ti_hash, node
    )
    return pod
