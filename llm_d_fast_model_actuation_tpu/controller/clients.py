"""Controller-side clients for the three data-plane HTTP surfaces.

The dual-pods controller talks to (reference SURVEY.md §3.2 boundaries):
  (b) the requester stub's SPI (chip discovery, memory, readiness relay),
  (c) the launcher REST API (instance CRUDL),
  (d) the engine admin port (/sleep, /wake_up, /is_sleeping — the calls that
      actually move tensors).

`Transports` is the seam: the HTTP implementation resolves a Pod to its IP
and speaks aiohttp; tests plug in-process fakes behind the same protocol.
Every HTTP call is latency-instrumented (fma_http_latency_seconds), matching
the reference's single doHTTP path (inference-server.go:2208-2253).
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, List, Optional, Protocol

import aiohttp

from ..api import constants as C
from ..utils import tracing
from .metrics import HTTP_LATENCY


class InstanceNotFound(Exception):
    pass


class LauncherHandle(Protocol):
    async def create_named_instance(self, instance_id: str, config: Dict[str, Any]) -> Dict[str, Any]: ...
    async def list_instances(self) -> Dict[str, Any]: ...
    async def get_instance(self, instance_id: str) -> Dict[str, Any]: ...
    async def delete_instance(self, instance_id: str) -> Dict[str, Any]: ...
    async def health(self) -> bool: ...


class SpiHandle(Protocol):
    async def accelerators(self) -> List[str]: ...
    async def accelerator_memory(self) -> Dict[str, int]: ...
    async def become_ready(self) -> None: ...
    async def become_unready(self) -> None: ...


class EngineHandle(Protocol):
    async def is_sleeping(self) -> bool: ...
    async def sleep(self, level: int = 1) -> None: ...
    async def wake_up(self) -> None: ...
    async def healthy(self) -> bool: ...


class Transports(Protocol):
    def launcher(self, pod: Dict[str, Any]) -> LauncherHandle: ...
    def requester_spi(self, pod: Dict[str, Any]) -> SpiHandle: ...
    def engine_admin(self, pod: Dict[str, Any], port: int) -> EngineHandle: ...


def pod_ip(pod: Dict[str, Any]) -> str:
    ip = ((pod.get("status") or {}).get("podIP")) or ""
    if not ip:
        raise RuntimeError(f"pod {pod['metadata']['name']} has no IP yet")
    return ip


@contextlib.contextmanager
def observe_http_latency(purpose: str, method: str):
    """Public wrapper around the fma_http_latency_seconds discipline, for
    callers doing controller-originated HTTP outside `_Http` (and for the
    metrics-catalog test to exercise the real instrumentation path)."""
    t0 = time.monotonic()
    try:
        yield
    finally:
        HTTP_LATENCY.labels(purpose=purpose, method=method).observe(
            time.monotonic() - t0
        )


class _Http:
    def __init__(self, session: Optional[aiohttp.ClientSession] = None) -> None:
        self._session = session

    async def session(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=30)
            )
        return self._session

    async def call(
        self, method: str, url: str, purpose: str, json_body=None
    ):
        s = await self.session()
        # One span per controller-originated call (same single-choke-point
        # discipline as fma_http_latency_seconds), propagated downstream
        # as a W3C traceparent so the launcher / engine / SPI side of the
        # hop joins the same trace (docs/tracing.md).
        with tracing.span(
            "controller.http", purpose=purpose, method=method
        ) as sp:
            headers = {}
            tp = sp.traceparent()
            if tp:
                headers["traceparent"] = tp
            t0 = time.monotonic()
            try:
                async with s.request(
                    method, url, json=json_body, headers=headers
                ) as resp:
                    body = await resp.read()
                    sp.set(status=resp.status)
                    return resp.status, body
            finally:
                HTTP_LATENCY.labels(purpose=purpose, method=method).observe(
                    time.monotonic() - t0
                )


class HttpLauncherHandle:
    def __init__(self, http: _Http, base: str) -> None:
        self._http = http
        self._base = base

    async def create_named_instance(self, instance_id: str, config: Dict[str, Any]) -> Dict[str, Any]:
        import json

        status, body = await self._http.call(
            "PUT",
            f"{self._base}/v2/vllm/instances/{instance_id}",
            "createInstance",
            json_body=config,
        )
        if status not in (200, 201):
            raise RuntimeError(f"create instance {instance_id}: {status} {body[:200]}")
        return json.loads(body)

    async def list_instances(self) -> Dict[str, Any]:
        import json

        status, body = await self._http.call(
            "GET", f"{self._base}/v2/vllm/instances", "listInstances"
        )
        if status != 200:
            raise RuntimeError(f"list instances: {status}")
        return json.loads(body)

    async def get_instance(self, instance_id: str) -> Dict[str, Any]:
        import json

        status, body = await self._http.call(
            "GET", f"{self._base}/v2/vllm/instances/{instance_id}", "getInstance"
        )
        if status == 404:
            raise InstanceNotFound(instance_id)
        if status != 200:
            raise RuntimeError(f"get instance: {status}")
        return json.loads(body)

    async def delete_instance(self, instance_id: str) -> Dict[str, Any]:
        import json

        status, body = await self._http.call(
            "DELETE", f"{self._base}/v2/vllm/instances/{instance_id}", "deleteInstance"
        )
        if status == 404:
            raise InstanceNotFound(instance_id)
        if status != 200:
            raise RuntimeError(f"delete instance: {status}")
        return json.loads(body)

    async def health(self) -> bool:
        try:
            status, _ = await self._http.call(
                "GET", f"{self._base}/health", "launcherHealth"
            )
            return status == 200
        except Exception:
            return False


class HttpSpiHandle:
    def __init__(self, http: _Http, base: str) -> None:
        self._http = http
        self._base = base

    async def accelerators(self) -> List[str]:
        import json

        from ..api import spi as spiapi

        status, body = await self._http.call(
            "GET", self._base + spiapi.ACCELERATOR_QUERY_PATH, "queryAccelerators"
        )
        if status != 200:
            raise RuntimeError(f"accelerator query: {status}")
        return list(json.loads(body))

    async def accelerator_memory(self) -> Dict[str, int]:
        import json

        from ..api import spi as spiapi

        status, body = await self._http.call(
            "GET",
            self._base + spiapi.ACCELERATOR_MEMORY_QUERY_PATH,
            "queryAcceleratorMemory",
        )
        if status != 200:
            raise RuntimeError(f"memory query: {status}")
        return {k: int(v) for k, v in json.loads(body).items()}

    async def become_ready(self) -> None:
        from ..api import spi as spiapi

        status, _ = await self._http.call(
            "POST", self._base + spiapi.BECOME_READY_PATH, "becomeReady"
        )
        if status != 200:
            raise RuntimeError(f"become-ready: {status}")

    async def become_unready(self) -> None:
        from ..api import spi as spiapi

        status, _ = await self._http.call(
            "POST", self._base + spiapi.BECOME_UNREADY_PATH, "becomeUnready"
        )
        if status != 200:
            raise RuntimeError(f"become-unready: {status}")


class HttpEngineHandle:
    def __init__(self, http: _Http, base: str) -> None:
        self._http = http
        self._base = base

    async def is_sleeping(self) -> bool:
        import json

        status, body = await self._http.call(
            "GET", self._base + C.ENGINE_IS_SLEEPING_PATH, "querySleeping"
        )
        if status != 200:
            raise RuntimeError(f"is_sleeping: {status}")
        return bool(json.loads(body).get("is_sleeping"))

    async def sleep(self, level: int = 1) -> None:
        status, _ = await self._http.call(
            "POST", f"{self._base}{C.ENGINE_SLEEP_PATH}?level={level}", "sleep"
        )
        if status != 200:
            raise RuntimeError(f"sleep: {status}")

    async def wake_up(self) -> None:
        status, _ = await self._http.call(
            "POST", self._base + C.ENGINE_WAKE_PATH, "wakeUp"
        )
        if status != 200:
            raise RuntimeError(f"wake_up: {status}")

    async def healthy(self) -> bool:
        try:
            status, _ = await self._http.call(
                "GET", f"{self._base}/health", "engineHealth"
            )
            return status == 200
        except Exception:
            return False


class HttpTransports:
    """Production transports: Pod IP + well-known ports."""

    def __init__(self) -> None:
        self._http = _Http()

    def launcher(self, pod: Dict[str, Any]) -> LauncherHandle:
        port = (pod["metadata"].get("annotations") or {}).get(
            C.LAUNCHER_PORT_ANNOTATION, C.LAUNCHER_SERVICE_PORT
        )
        return HttpLauncherHandle(
            self._http, f"http://{pod_ip(pod)}:{port}"
        )

    def requester_spi(self, pod: Dict[str, Any]) -> SpiHandle:
        port = (pod["metadata"].get("annotations") or {}).get(
            C.ADMIN_PORT_ANNOTATION, C.ADMIN_PORT_DEFAULT
        )
        return HttpSpiHandle(self._http, f"http://{pod_ip(pod)}:{port}")

    def engine_admin(self, pod: Dict[str, Any], port: int) -> EngineHandle:
        return HttpEngineHandle(self._http, f"http://{pod_ip(pod)}:{port}")

    async def close(self) -> None:
        if self._http._session is not None and not self._http._session.closed:
            await self._http._session.close()
