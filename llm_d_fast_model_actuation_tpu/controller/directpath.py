"""Direct-provider path (M2): server-patch rendering and nominal Pods.

In the direct path the user puts a *server patch* annotation on the
server-requesting Pod instead of naming an InferenceServerConfig. The
controller derives the server-providing Pod ("nominal Pod") from the
requester itself:

  requester spec --de-individualize--> base
  server-patch template --render(ProviderData)--> strategic-merge patch
  base + patch --merge--> provider spec
  + node pinning + TPU env injection + zeroed `google.com/tpu` resources
  + nominal-hash annotation (identity for sleeping-twin reuse)

Reference behavior being reproduced (TPU-first, not translated):
`getNominalServerProvidingPod` (pkg/controller/dual-pods/
inference-server.go:1842-1946), nominal hash at :1880-1888,
`DeIndividualize` (pkg/controller/utils/pod-helper.go:85-109), engine-port
discovery from the readiness probe (pod-helper.go:112-140), sleeper budget
(`enforceSleeperBudget`, inference-server.go:1353-1427).

TPU deltas: `CUDA_VISIBLE_DEVICES` (flat indices) becomes
`TPU_VISIBLE_DEVICES` + process-bounds env derived from the node's chip map
(ICI coordinates, not a flat index space), and `nvidia.com/gpu` becomes
`google.com/tpu`.
"""

from __future__ import annotations

import copy
import json
import re
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

from ..api import constants as C
from ..parallel.topology import ChipMap, HostTopology
from ..utils.hashing import canonical_json, sha256_hex

#: Annotation carrying the SHA-256 identity of a direct providing Pod:
#: hash(provider spec + chip IDs + node). Two requesters whose rendered
#: providers hash equal can share one (sleeping) provider.
NOMINAL_HASH_ANNOTATION = "dual-pods.llm-d.ai/nominal-hash"

#: Component label value for direct (non-launcher) providing Pods.
DIRECT_PROVIDER_COMPONENT = "server-provider"

#: Annotation recording when a direct provider was last unbound (seconds,
#: wall clock) — the LRU key for sleeper-budget eviction. Persisted on the
#: Pod so controller restarts don't reset eviction order.
LAST_USED_ANNOTATION = "dual-pods.llm-d.ai/last-used"

_TEMPLATE_FIELD = re.compile(r"\{\{\s*\.(\w+)\s*\}\}")


@dataclass
class ProviderData:
    """Data available to the server-patch template (inference-server.go's
    ProviderData)."""

    node_name: str
    local_volume: str = ""

    def fields(self) -> Dict[str, str]:
        return {"NodeName": self.node_name, "LocalVolume": self.local_volume}


def render_server_patch(template: str, data: ProviderData) -> Dict[str, Any]:
    """Render the ``{{.Field}}`` references and parse the result as a
    strategic-merge patch document (JSON, or YAML when available)."""
    fields = data.fields()

    def sub(m: "re.Match[str]") -> str:
        name = m.group(1)
        if name not in fields:
            raise ValueError(f"server-patch references unknown field .{name}")
        return fields[name]

    rendered = _TEMPLATE_FIELD.sub(sub, template)
    try:
        doc = json.loads(rendered)
    except json.JSONDecodeError as json_err:
        try:
            import yaml  # type: ignore
        except ImportError as e:  # pragma: no cover
            raise ValueError(f"server-patch is not valid JSON: {json_err}") from e
        try:
            doc = yaml.safe_load(rendered)
        except yaml.YAMLError as e:
            raise ValueError(f"server-patch is neither valid JSON nor YAML: {e}") from e
    if not isinstance(doc, dict):
        raise ValueError("server-patch must render to an object")
    return doc


# -------------------------------------------------------------- merge logic

#: list fields merged element-wise by this key (the subset of the strategic
#: merge-patch schema that Pod specs exercise).
_MERGE_KEYS = {
    "containers": "name",
    "initContainers": "name",
    "ephemeralContainers": "name",
    "volumes": "name",
    "env": "name",
    "volumeMounts": "mountPath",
    "ports": "containerPort",
}


def strategic_merge(base: Any, patch: Any, merge_key: Optional[str] = None) -> Any:
    """Strategic-merge `patch` into `base` (both unmodified; returns new).

    Dicts merge recursively; `null` deletes a key; lists whose field name has
    a merge key merge element-wise by that key (honoring the
    ``$patch: delete`` directive); other lists are replaced.
    """
    if isinstance(base, dict) and isinstance(patch, dict):
        out = dict(base)
        for k, v in patch.items():
            if v is None:
                out.pop(k, None)
            elif k in out:
                out[k] = strategic_merge(out[k], v, _MERGE_KEYS.get(k))
            else:
                out[k] = copy.deepcopy(v)
        return out
    if isinstance(base, list) and isinstance(patch, list) and merge_key:
        by_key = {e.get(merge_key): i for i, e in enumerate(base) if isinstance(e, dict)}
        out_list = [copy.deepcopy(e) for e in base]
        deletions: List[int] = []
        for e in patch:
            if not isinstance(e, dict) or merge_key not in e:
                out_list.append(copy.deepcopy(e))
                continue
            idx = by_key.get(e[merge_key])
            if e.get("$patch") == "delete":
                if idx is not None:
                    deletions.append(idx)
                continue
            if idx is None:
                out_list.append(copy.deepcopy(e))
            else:
                out_list[idx] = strategic_merge(out_list[idx], e)
        for idx in sorted(deletions, reverse=True):
            del out_list[idx]
        return out_list
    return copy.deepcopy(patch)


def de_individualize(pod: Dict[str, Any]) -> Dict[str, Any]:
    """Strip the parts of a Pod that are individual to one instance
    (pod-helper.go:85-109): the projected service-account token volume and
    its mounts, ephemeral containers, scheduling outcome, and status."""
    spec = copy.deepcopy(pod.get("spec") or {})
    spec.pop("ephemeralContainers", None)
    spec.pop("nodeName", None)
    api_vols = {
        v["name"]
        for v in spec.get("volumes", [])
        if v.get("name", "").startswith("kube-api-access-")
    }
    if api_vols:
        spec["volumes"] = [v for v in spec["volumes"] if v["name"] not in api_vols]
        for c in spec.get("containers", []) + spec.get("initContainers", []):
            if "volumeMounts" in c:
                c["volumeMounts"] = [
                    m for m in c["volumeMounts"] if m.get("name") not in api_vols
                ]
    return spec


def engine_port_of(pod_spec: Dict[str, Any]) -> int:
    """Engine port = the inference-server container's readiness-probe HTTP
    port (pod-helper.go:112-140). The probe port is a kube IntOrString: an
    int, a numeric string, or a named port resolved against the container's
    ports list; falls back to the first containerPort."""
    for c in pod_spec.get("containers", []):
        if c.get("name") != C.INFERENCE_SERVER_CONTAINER_NAME:
            continue
        ports = c.get("ports") or []
        probe = ((c.get("readinessProbe") or {}).get("httpGet") or {}).get("port")
        if isinstance(probe, int):
            return probe
        if isinstance(probe, str):
            if probe.isdigit():
                return int(probe)
            for p in ports:  # named port
                if p.get("name") == probe and isinstance(p.get("containerPort"), int):
                    return p["containerPort"]
        if ports and isinstance(ports[0].get("containerPort"), int):
            return ports[0]["containerPort"]
    return 8000


def chip_indices(
    chip_ids: Sequence[str], node: str, chip_map: Optional[ChipMap]
) -> List[int]:
    """chip IDs -> local indices via the chip map.

    When the node HAS a chip-map entry, an unknown chip ID is a hard error —
    silently guessing indices would point TPU_VISIBLE_DEVICES at chips the
    requester does not hold. The sorted-rank fallback applies only when no
    map entry exists at all (hardware-less tests).
    """
    if chip_map is not None:
        host = chip_map.host(node)
        if host is not None:
            try:
                return host.indices_for(chip_ids)
            except KeyError as e:
                raise ValueError(
                    f"chip id {e.args[0]!r} not in the chip map for node {node}"
                ) from e
    ranked = {cid: i for i, cid in enumerate(sorted(set(chip_ids)))}
    return [ranked[cid] for cid in chip_ids]


def nominal_provider_pod(
    req: Dict[str, Any],
    patch: Dict[str, Any],
    node: str,
    chip_ids: Sequence[str],
    chip_map: Optional[ChipMap] = None,
) -> Dict[str, Any]:
    """Build the nominal server-providing Pod for a direct-path requester.

    The returned Pod has no name/namespace yet; its nominal-hash annotation
    is the identity used for sleeping-twin lookup.
    """
    # normalize: the SPI may report the same chip set in any order, and the
    # order must not leak into the rendered spec (and thus the nominal hash)
    chip_ids = sorted(chip_ids)
    base = de_individualize(req)
    spec = strategic_merge(base, patch.get("spec") or {})

    # pin to the requester's node without consuming scheduler resources
    sel = spec.setdefault("nodeSelector", {})
    sel["kubernetes.io/hostname"] = node

    indices = chip_indices(chip_ids, node, chip_map)
    visible = ",".join(str(i) for i in indices)
    for c in spec.get("containers", []):
        if c.get("name") != C.INFERENCE_SERVER_CONTAINER_NAME:
            continue
        env = c.setdefault("env", [])
        for name, value in (
            (C.TPU_VISIBLE_DEVICES_ENV, visible),
            (C.TPU_PROCESS_BOUNDS_ENV, f"1,1,{max(1, len(indices))}"),
            (C.TPU_CHIPS_PER_PROCESS_BOUNDS_ENV, f"1,1,{max(1, len(indices))}"),
        ):
            for entry in env:
                if entry.get("name") == name:
                    entry["value"] = value
                    break
            else:
                env.append({"name": name, "value": value})
        # the provider must NOT request chips from the device plugin — the
        # requester already holds the allocation
        res = c.setdefault("resources", {})
        for section in ("limits", "requests"):
            if C.TPU_RESOURCE in (res.get(section) or {}):
                res[section][C.TPU_RESOURCE] = "0"

    meta_patch = patch.get("metadata") or {}
    pod: Dict[str, Any] = {
        "kind": "Pod",
        "metadata": {
            "labels": {
                **(req["metadata"].get("labels") or {}),
                **(meta_patch.get("labels") or {}),
                C.COMPONENT_LABEL: DIRECT_PROVIDER_COMPONENT,
            },
            "annotations": {
                **(meta_patch.get("annotations") or {}),
                C.ACCELERATORS_ANNOTATION: ",".join(sorted(chip_ids)),
                C.SERVER_PORT_ANNOTATION: str(engine_port_of(spec)),
            },
        },
        "spec": spec,
    }
    pod["metadata"]["annotations"][NOMINAL_HASH_ANNOTATION] = nominal_hash(
        spec, chip_ids, node
    )
    return pod


def nominal_hash(spec: Dict[str, Any], chip_ids: Sequence[str], node: str) -> str:
    """SHA-256 over (canonical provider spec, sorted chips, node) —
    inference-server.go:1880-1888."""
    return sha256_hex(
        canonical_json({"spec": spec, "chips": sorted(chip_ids), "node": node})
    )


def load_chip_map(store: Any, namespace: str) -> Optional[ChipMap]:
    """Parse the chip-map ConfigMap (the reference's `gpu-map`,
    controller.go:888-924) from the cluster store, if present."""
    cm = store.try_get("ConfigMap", namespace, C.CHIP_MAP_CONFIGMAP)
    if cm is None:
        return None
    try:
        return ChipMap.parse(cm.get("data") or {})
    except (ValueError, KeyError):
        return None
