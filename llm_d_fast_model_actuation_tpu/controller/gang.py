"""Slice-gang coordinator: actuating multi-host InferenceServerConfigs.

The reference's largest serving unit is one node's GPUs; a TPU slice can
span hosts (v5e-16 = 2 hosts x 2x4), served by ONE engine running as N
jax.distributed processes — one per host (SURVEY.md §7 hard part #5;
`parallel/multihost.py`). Under dual-pods that means a GANG of
requester/provider pairs. This controller owns the gang lifecycle:

  * **group**: gang-less requesters of a multi-host ISC — chips discovered
    (accelerators annotation stamped by the dual-pods controller), on
    distinct nodes — are grouped into gangs of exactly ``accelerator.hosts``
    members;
  * **plan**: the slice is planned from the chip-map ConfigMap (host
    shapes + ``origin:`` lines give each host's corner in global slice
    coordinates); planning failures are surfaced on the ISC status;
  * **stamp**: each member gets the gang id and its member coordination
    env (FMA_NUM_PROCESSES / FMA_PROCESS_ID / FMA_COORDINATOR_ADDRESS) as
    annotations. The dual-pods controller defers instance creation for
    multi-host requesters until the stamp exists, then merges the env into
    the engine instance config — jax.distributed.initialize in each child
    blocks until the whole gang joins, so readiness needs no extra gating;
  * **degrade**: an SPMD job cannot lose a process and continue. When a
    gang member disappears, the remaining members' requesters are deleted
    (UID preconditions — the relay pattern of inference-server.go:256-289)
    so their ReplicaSet re-creates them and a fresh gang forms.

The coordinator address uses the process-0 member's requester Pod IP:
on TPU hosts the requester and its provider run hostNetwork, so the node
address is stable across the pair (and in the TPU-less e2e everything is
loopback).
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, Dict, List, Optional, Tuple

from ..api import constants as C
from ..api.types import InferenceServerConfig
from ..parallel.multihost import (
    COORDINATOR_PORT,
    SlicePlanError,
    plan_slice,
)
from ..parallel.topology import HostTopology
from .directpath import load_chip_map
from .store import Conflict, NotFound

logger = logging.getLogger(__name__)

#: Gang id a member belongs to (short content hash; a fresh grouping mints
#: a fresh id, so stale stamps are detectable).
GANG_ANNOTATION = "dual-pods.llm-d.ai/slice-gang"
#: JSON env this member's engine child needs to join the gang.
GANG_ENV_ANNOTATION = "dual-pods.llm-d.ai/slice-gang-env"


#: Exactly the env keys the coordinator stamps (coordination_env + gang id).
GANG_ENV_KEYS = (
    "FMA_NUM_PROCESSES",
    "FMA_PROCESS_ID",
    "FMA_COORDINATOR_ADDRESS",
    "FMA_GANG_ID",
)


def gang_env_from_instance_env(
    env_vars: Optional[Dict[str, Any]],
) -> Optional[Dict[str, str]]:
    """Recover the gang env from a committed engine-instance config's
    env_vars. Obsolescence checks recompute the instance identity
    (utils/hashing.instance_id_for) and must hash the SAME extra_env the
    creation path used, else every gang instance would self-mismatch.

    FMA_GANG_ID is the discriminator: the coordinator always stamps it,
    while an operator hand-wiring coordination env into a single-host
    ISC's env_vars (resolve_distributed reads those too) never does —
    without it the keys are ISC-authored env, hashed as part of the spec
    already, and returning them here would make a healthy single-host
    instance permanently self-mismatch."""
    env_vars = env_vars or {}
    if "FMA_GANG_ID" not in env_vars:
        return None
    return {
        str(k): str(v) for k, v in env_vars.items() if k in GANG_ENV_KEYS
    }


def gang_env_of(pod: Dict[str, Any]) -> Optional[Dict[str, str]]:
    """The member coordination env stamped on a requester, if any."""
    ann = (pod.get("metadata") or {}).get("annotations") or {}
    raw = ann.get(GANG_ENV_ANNOTATION, "")
    if not raw:
        return None
    try:
        env = json.loads(raw)
    except ValueError:
        return None
    return {str(k): str(v) for k, v in env.items()}


def is_multihost(isc: InferenceServerConfig) -> bool:
    return isc.spec.engine_server_config.accelerator.hosts > 1


class SliceGangCoordinator:
    """Watches requesters of multi-host ISCs; forms, stamps, and degrades
    gangs. Store-agnostic like the other controllers."""

    def __init__(
        self,
        store: Any,
        namespace: str,
        coordinator_port: int = COORDINATOR_PORT,
    ) -> None:
        self.store = store
        self.ns = namespace
        self.port = coordinator_port
        self._queue: asyncio.Queue = asyncio.Queue()
        self._queued: set = set()
        self._task: Optional[asyncio.Task] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._unsub = None
        self._stopping = False

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._unsub = self.store.subscribe(self._on_event)
        self._task = self._loop.create_task(self._run())
        # initial sync: every multi-host ISC present at startup
        for obj in self.store.list(InferenceServerConfig.KIND, self.ns):
            self._enqueue(obj["metadata"]["name"])

    async def stop(self) -> None:
        self._stopping = True
        if self._unsub:
            self._unsub()
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    def _on_event(self, event: str, obj: Dict[str, Any]) -> None:
        md = obj.get("metadata") or {}
        if md.get("namespace") != self.ns:
            return
        kind = obj.get("kind")
        if kind == InferenceServerConfig.KIND:
            self._enqueue(md["name"])
        elif kind == "Pod":
            isc = (md.get("annotations") or {}).get(
                C.INFERENCE_SERVER_CONFIG_ANNOTATION
            )
            if isc:
                self._enqueue(isc)

    def _enqueue(self, isc_name: str) -> None:
        # Store subscribers run on whichever thread commits the write (our
        # own mutations run via asyncio.to_thread) — asyncio.Queue is not
        # thread-safe, so hop onto the loop like the sibling controllers do.
        loop = self._loop
        if loop is None or loop.is_closed():
            return

        def put() -> None:
            if isc_name in self._queued:
                return
            self._queued.add(isc_name)
            self._queue.put_nowait(isc_name)

        try:
            loop.call_soon_threadsafe(put)
        except RuntimeError:  # loop gone during shutdown
            pass

    async def _run(self) -> None:
        while not self._stopping:
            isc_name = await self._queue.get()
            self._queued.discard(isc_name)
            try:
                await self._reconcile(isc_name)
            except Exception:
                logger.exception("gang reconcile %s failed", isc_name)
                await asyncio.sleep(0.5)
                self._enqueue(isc_name)

    # -- reconcile -----------------------------------------------------------

    async def _reconcile(self, isc_name: str) -> None:
        obj = self.store.try_get(InferenceServerConfig.KIND, self.ns, isc_name)
        if obj is None:
            return
        isc = InferenceServerConfig.from_dict(obj)
        if not is_multihost(isc):
            return
        hosts_needed = isc.spec.engine_server_config.accelerator.hosts

        members: List[Dict[str, Any]] = []
        for pod in self.store.list("Pod", self.ns):
            md = pod.get("metadata") or {}
            ann = md.get("annotations") or {}
            if ann.get(C.INFERENCE_SERVER_CONFIG_ANNOTATION) != isc_name:
                continue
            if md.get("deletionTimestamp"):
                continue
            members.append(pod)

        # ---- degrade broken gangs ------------------------------------------
        by_gang: Dict[str, List[Dict[str, Any]]] = {}
        for pod in members:
            gid = (pod["metadata"].get("annotations") or {}).get(
                GANG_ANNOTATION
            )
            if gid:
                by_gang.setdefault(gid, []).append(pod)
        for gid, pods in by_gang.items():
            if len(pods) >= hosts_needed:
                continue
            # a member is gone: the SPMD job is dead — relay-delete the rest
            for pod in pods:
                md = pod["metadata"]
                logger.info(
                    "gang %s degraded (%d/%d members): deleting %s",
                    gid, len(pods), hosts_needed, md["name"],
                )
                try:
                    await asyncio.to_thread(
                        self.store.delete,
                        "Pod", self.ns, md["name"],
                        expect_uid=md.get("uid"),
                    )
                except (NotFound, Conflict):
                    pass

        # ---- form a new gang from unassigned members -----------------------
        unassigned = [
            p
            for p in members
            if not (p["metadata"].get("annotations") or {}).get(GANG_ANNOTATION)
            and (p["metadata"].get("annotations") or {}).get(
                C.ACCELERATORS_ANNOTATION
            )
            and (p.get("spec") or {}).get("nodeName")
        ]
        # one candidate per node (two requesters of one ISC on one node
        # can't be in the same gang)
        by_node: Dict[str, Dict[str, Any]] = {}
        for p in sorted(unassigned, key=lambda p: p["metadata"]["name"]):
            by_node.setdefault(p["spec"]["nodeName"], p)
        if len(by_node) < hosts_needed:
            # not enough members yet; pod events re-enqueue us. Clear any
            # stale planning error — the world has changed since it was set.
            await self._set_status(isc_name, [])
            return

        topo = isc.spec.engine_server_config.accelerator.topology
        if not topo:
            await self._set_status(
                isc_name,
                ["multi-host ISC must declare accelerator.topology (the "
                 "global slice shape)"],
            )
            return
        chip_map = load_chip_map(self.store, self.ns)
        if chip_map is None:
            await self._set_status(
                isc_name,
                ["multi-host ISC needs the chip-map ConfigMap (host "
                 "origins) to plan the slice"],
            )
            return

        # Select within ONE physical slice (hosts of different slices share
        # origin coordinates but no ICI — a gang must never span slice
        # ids), then by slice origin: one host per origin cell
        # (alphabetical tie-break), lexicographic origins starting at the
        # zero corner. Extra candidates — hosts of another slice, unmapped
        # nodes — must not poison the selection.
        by_slice: Dict[str, Dict[Tuple[int, ...], str]] = {}
        for node in sorted(by_node):
            if chip_map.host(node) is None:
                continue  # unmapped node can't be planned; skip
            by_slice.setdefault(chip_map.slice_id(node), {}).setdefault(
                tuple(chip_map.origin(node)), node
            )
        chosen: Dict[str, Dict[str, Any]] = {}
        for _, by_origin in sorted(by_slice.items()):
            origins = sorted(by_origin)
            if len(origins) < hosts_needed or any(o != 0 for o in origins[0]):
                continue  # this slice can't field a gang yet
            chosen = {
                by_origin[o]: by_node[by_origin[o]]
                for o in origins[:hosts_needed]
            }
            break
        if not chosen:
            await self._set_status(isc_name, [])  # waiting, not an error
            return

        plan_input: Dict[str, Tuple[Tuple[int, ...], HostTopology]] = {}
        for node, pod in chosen.items():
            host = chip_map.host(node)
            reported = (
                pod["metadata"]["annotations"][C.ACCELERATORS_ANNOTATION]
            ).split(",")
            by_id = host.by_id()
            missing = [c for c in reported if c not in by_id]
            if missing:
                await self._set_status(
                    isc_name,
                    [f"node {node}: chips {missing} absent from chip-map"],
                )
                return
            local = HostTopology(
                topology=host.topology,
                chips=[by_id[c] for c in reported],
            )
            plan_input[node] = (chip_map.origin(node), local)

        try:
            plan = plan_slice(topo, plan_input)
        except SlicePlanError as e:
            await self._set_status(isc_name, [f"slice planning: {e}"])
            return

        coord_pod = chosen[plan.coordinator_node]
        coord_ip = (coord_pod.get("status") or {}).get("podIP", "")
        if not coord_ip:
            await self._set_status(isc_name, [])
            return  # no IP yet; pod update re-enqueues us

        import secrets

        gid = f"g{secrets.token_hex(4)}"
        # Per-gang coordinator port: a degraded gang's process-0 engine may
        # still be alive (asleep) holding the old port on hostNetwork; a
        # fixed port would make the next gang's bind fail. Derived from the
        # gang id so all members agree without another round-trip. A
        # residual collision (1/4096) self-heals through the crash relay:
        # the bind-failed engine goes STOPPED -> notifier -> controller
        # deletes the requester -> this gang degrades -> the re-formed gang
        # draws a fresh gid and port.
        port = self.port + int(gid[1:], 16) % 4096
        for node, pod in chosen.items():
            assignment = plan.assignment_for(node)
            env = plan.coordination_env(assignment.process_id, coord_ip, port)
            # the gang id makes the env — and therefore the engine instance
            # identity (utils/hashing.instance_id_for) — unique per gang: a
            # sleeping member of a dead gang must never be woken into a new
            # gang (jax.distributed.initialize cannot re-run in-process)
            env["FMA_GANG_ID"] = gid
            name = pod["metadata"]["name"]

            def stamp(p, env=env):
                ann = p["metadata"].setdefault("annotations", {})
                if ann.get(GANG_ANNOTATION):
                    return None  # raced: someone stamped already
                ann[GANG_ANNOTATION] = gid
                ann[GANG_ENV_ANNOTATION] = json.dumps(env, sort_keys=True)
                return p

            try:
                await asyncio.to_thread(
                    self.store.mutate, "Pod", self.ns, name, stamp
                )
            except (NotFound, Conflict):
                # member vanished mid-stamp: the partial gang will degrade
                # on the next event
                return
        await self._set_status(isc_name, [])
        logger.info(
            "gang %s formed for %s: %s",
            gid, isc_name,
            [(h.node, h.process_id) for h in plan.hosts],
        )

    async def _set_status(self, isc_name: str, errors: List[str]) -> None:
        def apply(obj):
            status = obj.setdefault("status", {})
            cur = status.get("gangErrors") or []
            if cur == errors:
                return None
            status["gangErrors"] = errors
            return obj

        try:
            await asyncio.to_thread(
                self.store.mutate,
                InferenceServerConfig.KIND, self.ns, isc_name, apply,
            )
        except (NotFound, Conflict):
            pass
