"""Control-plane CLI: `python -m llm_d_fast_model_actuation_tpu.controller`.

Subcommands mirror the reference's two controller binaries
(cmd/dual-pods-controller/main.go:40-119, cmd/launcher-populator/
main.go:42-140) and the chart's args (deploy/chart). The cluster store
backend is selected by --store:

  memory  — in-process store (demo / single-process integration runs; the
            launcher/requester/engine transports are still real HTTP)
  kube    — list+watch informer cache + REST writes against a
            kube-apiserver (kubestore.KubeStore): in-cluster service-account
            wiring by default, or --kube-api-url/--kube-token-file for an
            explicit endpoint.
"""

from __future__ import annotations

import argparse
import asyncio
import logging


def _common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--namespace", required=True, help="namespace to watch (controllers are namespace-scoped)")
    p.add_argument("--store", choices=["memory", "kube"], default="kube")
    p.add_argument("--kube-api-url", default="", help="apiserver URL (default: in-cluster)")
    p.add_argument("--kube-token-file", default="", help="bearer token file (with --kube-api-url)")
    p.add_argument("--kube-ca-file", default="", help="CA bundle (with --kube-api-url)")
    p.add_argument("--metrics-port", type=int, default=8002)
    p.add_argument("--log-level", default="info")


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="fma-tpu-controllers")
    sub = p.add_subparsers(dest="cmd", required=True)

    dpc = sub.add_parser("dual-pods-controller", help="bind requesters to providers")
    _common(dpc)
    dpc.add_argument("--sleeper-limit", type=int, default=1)
    dpc.add_argument("--accelerator-sleeping-memory-limit-bytes", type=int, default=0)
    dpc.add_argument(
        "--disable-slice-gangs",
        action="store_true",
        help="don't run the slice-gang coordinator (multi-host ISCs will "
        "never actuate)",
    )

    pop = sub.add_parser("launcher-populator", help="proactive launcher population")
    _common(pop)
    pop.add_argument("--expectation-timeout", type=float, default=5.0)
    pop.add_argument("--stuck-scheduling-threshold", type=float, default=120.0)
    pop.add_argument("--stuck-starting-threshold", type=float, default=450.0)

    args = p.parse_args(argv)
    logging.basicConfig(level=getattr(logging, args.log_level.upper(), logging.INFO))

    from .metrics import serve_metrics

    if args.store == "kube":
        from .kubestore import KubeStore

        if args.kube_api_url:
            store = KubeStore(
                args.kube_api_url,
                args.namespace,
                # pass the FILE: bound SA tokens rotate, KubeStore re-reads
                # per request
                token_file=args.kube_token_file or None,
                ca_file=args.kube_ca_file or None,
            )
        else:
            try:
                store = KubeStore.in_cluster(args.namespace)
            except (KeyError, OSError) as e:
                p.error(
                    f"not running in-cluster ({e}); pass --kube-api-url or "
                    "--store=memory"
                )
    else:
        from .store import InMemoryStore

        store = InMemoryStore()
    serve_metrics(args.metrics_port)

    async def run() -> None:
        if hasattr(store, "start"):
            await store.start()
        gang = None
        if args.cmd == "dual-pods-controller":
            from .clients import HttpTransports
            from .dualpods import DualPodsConfig, DualPodsController

            ctl = DualPodsController(
                store,
                HttpTransports(),
                DualPodsConfig(
                    namespace=args.namespace,
                    sleeper_limit=args.sleeper_limit,
                    accelerator_sleeping_memory_limit_bytes=args.accelerator_sleeping_memory_limit_bytes,
                ),
            )
            if not args.disable_slice_gangs:
                from .gang import SliceGangCoordinator

                gang = SliceGangCoordinator(store, args.namespace)
        else:
            from .populator import Populator, PopulatorConfig

            ctl = Populator(
                store,
                PopulatorConfig(
                    namespace=args.namespace,
                    expectation_timeout_s=args.expectation_timeout,
                    stuck_scheduling_threshold_s=args.stuck_scheduling_threshold,
                    stuck_starting_threshold_s=args.stuck_starting_threshold,
                ),
            )
        await ctl.start()
        if gang is not None:
            await gang.start()
        # readiness = initial batch processed (knows-processed-sync):
        # destructive decisions are safe only after one pass over the world
        await ctl.initial_sync.wait()
        logging.getLogger(__name__).info(
            "initial batch processed; controller ready"
        )
        try:
            await asyncio.Event().wait()  # serve forever
        finally:
            if gang is not None:
                await gang.stop()
            await ctl.stop()
            if hasattr(store, "stop"):
                await store.stop()

    asyncio.run(run())


if __name__ == "__main__":
    main()
